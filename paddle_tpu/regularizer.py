"""paddle_tpu.regularizer — public weight-decay regularizer classes.

Parity anchor: python/paddle/regularizer.py (L1Decay at :51, L2Decay at
:169) — both carry ``_coeff`` and are accepted by optimizers' ``weight_decay``
argument (optimizer/optimizer.py duck-types the coefficient) and by
``ParamAttr(regularizer=...)``. ``__call__(param)`` returns the decay term
added to the gradient: ``coeff * sign(param)`` for L1 (the gradient of
``coeff * sum(|x|)``), ``coeff * param`` for L2 (gradient of
``0.5 * coeff * sum(x^2)``).
"""

from __future__ import annotations

import jax.numpy as jnp

from .core.tensor import Tensor

__all__ = ["WeightDecayRegularizer", "L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    def __call__(self, param):
        raise NotImplementedError

    def __str__(self):
        raise NotImplementedError


def _arr(p):
    return p._data if isinstance(p, Tensor) else jnp.asarray(p)


class L1Decay(WeightDecayRegularizer):
    """loss += coeff * sum(|param|); grad contribution coeff * sign(param)
    (regularizer.py:51)."""

    def __init__(self, coeff: float = 0.0):
        self._coeff = float(coeff)
        self._regularization_coeff = self._coeff  # legacy attribute name

    def __call__(self, param):
        return self._coeff * jnp.sign(_arr(param))

    def __str__(self):
        return f"L1Decay, coeff={self._coeff:f}"


class L2Decay(WeightDecayRegularizer):
    """loss += 0.5 * coeff * sum(param^2); grad contribution coeff * param
    (regularizer.py:169)."""

    def __init__(self, coeff: float = 0.0):
        self._coeff = float(coeff)
        self._regularization_coeff = self._coeff

    def __call__(self, param):
        return self._coeff * _arr(param)

    def __str__(self):
        return f"L2Decay, coeff={self._coeff:f}"
