"""Global RNG state bridging Paddle's implicit-seed model onto JAX PRNG keys.

Reference: python/paddle/framework/random.py (global generator seeded by
``paddle.seed``). TPU-native: a process-global PRNG key that random ops split from.
Inside traced code (jit / shard_map), an explicit key context should be pushed with
``rng_guard(key)`` so randomness is a function of traced inputs, not trace-time state
— this is what the Trainer/DataLoader integration does per step.
"""

from __future__ import annotations

import contextlib
import threading

import jax

_state = threading.local()
# key created LAZILY: building it at import would initialize the XLA backend,
# which must not happen before jax.distributed.initialize in multi-host boot
_global = {"key": None, "seed": 0, "seeded": False}
_host_counter = [0]


def _key():
    if _global["key"] is None:
        _global["key"] = jax.random.key(_global["seed"])
    return _global["key"]


def seed(s: int):
    """Set the global RNG seed (paddle.seed)."""
    _global["key"] = jax.random.key(int(s))
    _global["seed"] = int(s)
    _global["seeded"] = True
    _host_counter[0] = 0  # next_host_seed() restarts: re-seeding reproduces runs
    return _global["seed"]


def explicitly_seeded() -> bool:
    """Has paddle.seed() ever been called in this process? Stochastic ops
    recorded without an explicit seed are not reproducible run-to-run — the
    trace-hazard linter flags them (PT-TRACE-003)."""
    return bool(_global["seeded"])


def get_rng_state():
    return _key()


def set_rng_state(key):
    _global["key"] = key
    # restoring a saved key is an explicit seeding decision — the run is
    # reproducible, so the trace linter must not flag PT-TRACE-003
    _global["seeded"] = True


def _ctx_stack():
    if not hasattr(_state, "stack"):
        _state.stack = []
    return _state.stack


@contextlib.contextmanager
def rng_guard(key):
    """Push an explicit PRNG key; random ops inside split from it deterministically."""
    stack = _ctx_stack()
    stack.append({"key": key, "count": 0})
    try:
        yield
    finally:
        stack.pop()


def next_host_seed() -> int:
    """A fresh uint32 host-side seed, reproducible under ``paddle.seed``.
    Used by the static Executor to parameterize per-run randomness."""
    _host_counter[0] += 1
    return (hash((_global["seed"], _host_counter[0]))) & 0xFFFFFFFF


def next_key():
    """Produce a fresh PRNG key (splitting the active context or the global state)."""
    stack = _ctx_stack()
    if stack:
        top = stack[-1]
        top["count"] += 1
        return jax.random.fold_in(top["key"], top["count"])
    k1, k2 = jax.random.split(_key())
    _global["key"] = k1
    return k2
