"""paddle.save / paddle.load (reference: python/paddle/framework/io.py:773,1020).

Serialization format: pickle of a pytree where every Tensor is replaced by a numpy
array (host transfer) — compatible across devices and loadable without TPU access.
"""

from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

from ..core.tensor import Tensor


def _to_saveable(obj: Any):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._data), str(np.dtype(obj.dtype)))
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_saveable(v) for v in obj)
    return obj


class _TensorPayload:
    def __init__(self, array, dtype):
        self.array = array
        self.dtype = dtype


def _from_saveable(obj: Any, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        if return_numpy:
            return obj.array
        return Tensor(obj.array, dtype=obj.dtype)
    if isinstance(obj, dict):
        return {k: _from_saveable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_from_saveable(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_saveable(obj, return_numpy)
