"""Jit-safe numeric guard — a checkify-style on-device health word.

The most common way a long TPU run dies is numerical: a NaN gradient, a
loss spike, or a poisoned batch silently corrupts optimizer state thousands
of steps before anyone looks at a curve. A naive per-step host-side
``isnan`` check serializes the device; JAX's ``checkify`` shows the fix —
functionalize the error flags so detection stays on-device and the host
reads ONE aggregated scalar per step.

This module is the device side of that contract:

- :func:`guard_step` — a pure combinator traced into the jitted train step.
  It folds every per-tensor reduction into a single int32 *health word*
  (bitmask below) and advances an EMA/deviation loss-spike detector carried
  as a tiny state vector. No host syncs happen inside; the single transfer
  is the caller fetching the word (which rides the same sync as the loss).
- :class:`GuardPolicy` — what the host does about a non-zero word
  (WARN / SKIP_STEP / ROLLBACK / ABORT, skip budget, LR re-warm after
  rollback). Consumed by ``distributed.resilience.watchdog.NumericWatchdog``.
- the *eager* health word — a process-global bitmask that host-side checks
  (``AmpScaler``'s overflow scan, ``amp.debugging.check_numerics``, the
  eager dispatcher's ``check_nan_inf``) report into, so eager and jitted
  anomalies land in one place.
- :class:`BadBatchRecorder` — dumps the offending batch + step + rng seed +
  health word to ``<dir>/step_<n>/`` for ``tools/replay_batch.py``.

Health-word bits and their diagnostic codes (docs/NUMERIC_GUARD.md):

=========  ===  ==========  ================================================
bit        val  code        meaning
=========  ===  ==========  ================================================
NAN_GRAD   1    PT-NUM-001  NaN in gradients (or eager op outputs)
INF_GRAD   2    PT-NUM-002  Inf in gradients (or eager op outputs)
NAN_LOSS   4    PT-NUM-003  loss is NaN/Inf
SPIKE      8    PT-NUM-004  loss exceeded EMA + k * deviation (post-warmup)
OVERFLOW   16   PT-NUM-005  AMP loss-scale overflow (``found_inf``)
=========  ===  ==========  ================================================
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "NAN_GRAD", "INF_GRAD", "NAN_LOSS", "SPIKE", "OVERFLOW", "ALL_BITS",
    "BIT_NAMES", "BIT_CODES", "describe_health", "health_codes",
    "guard_init_state", "guard_step", "GuardPolicy", "NumericAnomalyError",
    "record_health", "consume_health", "peek_health", "health_events",
    "BadBatchRecorder", "INJECT_NONE", "INJECT_NAN_GRAD",
    "INJECT_LOSS_SPIKE", "SPIKE_INJECT_FACTOR",
]

NAN_GRAD = 1
INF_GRAD = 2
NAN_LOSS = 4
SPIKE = 8
OVERFLOW = 16
ALL_BITS = NAN_GRAD | INF_GRAD | NAN_LOSS | SPIKE | OVERFLOW

BIT_NAMES = {
    NAN_GRAD: "NAN_GRAD",
    INF_GRAD: "INF_GRAD",
    NAN_LOSS: "NAN_LOSS",
    SPIKE: "SPIKE",
    OVERFLOW: "OVERFLOW",
}
BIT_CODES = {
    NAN_GRAD: "PT-NUM-001",
    INF_GRAD: "PT-NUM-002",
    NAN_LOSS: "PT-NUM-003",
    SPIKE: "PT-NUM-004",
    OVERFLOW: "PT-NUM-005",
}

# in-graph fault-injection codes (distributed.resilience.faults maps the
# FaultPlan actions nan_grad/loss_spike onto these; 0 = no fault). The codes
# arrive as a traced scalar argument, so injection never retraces.
INJECT_NONE = 0
INJECT_NAN_GRAD = 1
INJECT_LOSS_SPIKE = 2
SPIKE_INJECT_FACTOR = 1024.0


def describe_health(word: int) -> str:
    """``"NAN_GRAD|SPIKE (PT-NUM-001, PT-NUM-004)"`` for a non-zero word."""
    word = int(word)
    if not word:
        return "healthy"
    names = [n for b, n in BIT_NAMES.items() if word & b]
    codes = [c for b, c in BIT_CODES.items() if word & b]
    return "|".join(names) + " (" + ", ".join(codes) + ")"


def health_codes(word: int) -> List[str]:
    return [c for b, c in BIT_CODES.items() if int(word) & b]


# ---------------------------------------------------------------------------
# on-device guard (traced into the jitted train step)
# ---------------------------------------------------------------------------

def guard_init_state():
    """Fresh spike-detector state: ``[loss_ema, dev_ema, n_healthy]``."""
    import jax.numpy as jnp

    return jnp.zeros((3,), jnp.float32)


def guard_step(loss, grads, state, *, spike_factor: float = 10.0,
               warmup_steps: int = 5, ema_beta: float = 0.9):
    """Compute the step's health word on device; returns ``(word, state')``.

    Pure and jit-traceable: the per-tensor nan/inf reductions fold into one
    int32 scalar (under pjit that is one aggregated all-reduce — no
    per-tensor host syncs), and the EMA/deviation spike detector advances
    only on healthy steps so an anomalous loss can never poison its own
    detector. ``spike_factor``/``warmup_steps`` are trace-time constants.
    """
    import jax.numpy as jnp

    loss32 = jnp.asarray(loss, jnp.float32)
    nan_loss = jnp.logical_not(jnp.isfinite(loss32))

    has_nan = jnp.zeros((), bool)
    has_inf = jnp.zeros((), bool)
    for g in grads:
        g32 = jnp.asarray(g, jnp.float32) if g.dtype != jnp.float32 else g
        has_nan = jnp.logical_or(has_nan, jnp.isnan(g32).any())
        has_inf = jnp.logical_or(has_inf, jnp.isinf(g32).any())

    ema, dev, n = state[0], state[1], state[2]
    warm = n >= float(warmup_steps)
    # deviation floor: a perfectly flat loss must not make every wiggle a
    # spike — scale-relative epsilon keeps the threshold meaningful
    dev_floor = jnp.maximum(dev, 0.01 * jnp.abs(ema) + 1e-6)
    spike = jnp.logical_and(
        jnp.logical_and(warm, jnp.isfinite(loss32)),
        loss32 > ema + float(spike_factor) * dev_floor)

    word = (has_nan.astype(jnp.int32) * NAN_GRAD
            + has_inf.astype(jnp.int32) * INF_GRAD
            + nan_loss.astype(jnp.int32) * NAN_LOSS
            + spike.astype(jnp.int32) * SPIKE)

    healthy = word == 0
    first = n == 0
    beta = float(ema_beta)
    upd_ema = jnp.where(first, loss32, beta * ema + (1.0 - beta) * loss32)
    upd_dev = jnp.where(first, jnp.zeros((), jnp.float32),
                        beta * dev + (1.0 - beta) * jnp.abs(loss32 - ema))
    new_ema = jnp.where(healthy, upd_ema, ema)
    new_dev = jnp.where(healthy, upd_dev, dev)
    new_n = n + healthy.astype(jnp.float32)
    return word, jnp.stack([new_ema, new_dev, new_n])


# ---------------------------------------------------------------------------
# policy
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class GuardPolicy:
    """What to do when the health word is non-zero.

    ``action`` is the first response; SKIP_STEP escalates to ROLLBACK once
    more than ``max_skips_per_window`` anomalies land inside ``window``
    steps, and ROLLBACK escalates to ABORT after ``max_rollbacks``. After a
    rollback the learning rate re-warms linearly over ``rewarm_steps``
    steps (0 = no re-warm — required when a drill asserts the post-rollback
    trajectory matches an uninterrupted run).
    """

    WARN = "warn"
    SKIP_STEP = "skip_step"
    ROLLBACK = "rollback"
    ABORT = "abort"

    action: str = "skip_step"
    max_skips_per_window: int = 3
    window: int = 100
    max_rollbacks: int = 3
    rewarm_steps: int = 0
    spike_factor: float = 10.0
    warmup_steps: int = 5
    record_bad_batches: bool = True

    def __post_init__(self):
        if self.action not in (self.WARN, self.SKIP_STEP, self.ROLLBACK,
                               self.ABORT):
            raise ValueError(f"unknown guard action {self.action!r}")

    @property
    def skip_mask(self) -> int:
        """Bits that zero-apply the update in-graph. WARN observes only —
        the anomalous update is applied, everything else protects state."""
        return 0 if self.action == self.WARN else ALL_BITS


class NumericAnomalyError(RuntimeError):
    """A numeric anomaly escalated past its policy (ABORT, or budgets
    exhausted). Carries the health ``word`` and its PT-NUM ``codes``."""

    def __init__(self, word: int, step: Optional[int] = None, detail: str = ""):
        self.word = int(word)
        self.step = step
        self.codes = health_codes(word)
        at = f" at step {step}" if step is not None else ""
        extra = f": {detail}" if detail else ""
        super().__init__(
            f"numeric anomaly{at}: {describe_health(word)}{extra}")


# ---------------------------------------------------------------------------
# eager health word (host-side checks report here)
# ---------------------------------------------------------------------------

_EAGER_LOCK = threading.Lock()
_EAGER: Dict[str, object] = {"word": 0, "events": []}
_MAX_EVENTS = 256


def record_health(bits: int, source: str = "") -> None:
    """OR ``bits`` into the process-global eager health word. Called by
    AmpScaler's overflow scan, check_numerics, and the eager dispatcher's
    check_nan_inf so every detection channel lands in one word."""
    with _EAGER_LOCK:
        _EAGER["word"] = int(_EAGER["word"]) | int(bits)
        ev: List = _EAGER["events"]  # type: ignore[assignment]
        if len(ev) < _MAX_EVENTS:
            ev.append((int(bits), source))


def report_nan_inf(num_nan: int, num_inf: int, source: str = "") -> int:
    """Map host-side nan/inf counts onto the PT-NUM-001/002 bits and record
    them — the one home for the eager-check -> health-word mapping (used by
    the eager dispatcher's check_nan_inf and amp.debugging.check_numerics).
    Returns the bits (0 when both counts are zero)."""
    bits = (NAN_GRAD if num_nan else 0) | (INF_GRAD if num_inf else 0)
    if bits:
        record_health(bits, source)
    return bits


def consume_health() -> int:
    """Read-and-clear the eager health word (one consumer per step)."""
    with _EAGER_LOCK:
        word = int(_EAGER["word"])
        _EAGER["word"] = 0
        _EAGER["events"] = []
    return word


def peek_health() -> int:
    with _EAGER_LOCK:
        return int(_EAGER["word"])


def health_events() -> List[Tuple[int, str]]:
    with _EAGER_LOCK:
        return list(_EAGER["events"])  # type: ignore[arg-type]


# ---------------------------------------------------------------------------
# bad-batch capture
# ---------------------------------------------------------------------------

class BadBatchRecorder:
    """Dump an offending batch for offline replay.

    Each capture lands in ``<root>/step_<n>/`` as ``batch.npz`` (the raw
    host arrays) plus ``meta.json`` (step, health word, bit names, PT-NUM
    codes, rng seed, free-form extra). ``tools/replay_batch.py`` consumes
    the pair to reproduce the anomaly in isolation.
    """

    def __init__(self, root: str):
        self.root = str(root)

    def _dir(self, step: int) -> str:
        return os.path.join(self.root, f"step_{int(step):08d}")

    def record(self, step: int, word: int, arrays: Dict[str, object], *,
               rng_seed: Optional[int] = None, extra: Optional[dict] = None
               ) -> str:
        d = self._dir(step)
        os.makedirs(d, exist_ok=True)
        np.savez(os.path.join(d, "batch.npz"),
                 **{k: np.asarray(v) for k, v in arrays.items()})
        meta = {
            "step": int(step),
            "health_word": int(word),
            "bits": [n for b, n in BIT_NAMES.items() if int(word) & b],
            "codes": health_codes(word),
            "rng_seed": rng_seed,
            "arrays": sorted(arrays),
            "extra": extra or {},
        }
        tmp = os.path.join(d, ".meta.json.tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f, indent=1)
        os.replace(tmp, os.path.join(d, "meta.json"))  # meta lands last, atomically
        return d

    def steps(self) -> List[int]:
        if not os.path.isdir(self.root):
            return []
        out = []
        for name in os.listdir(self.root):
            if name.startswith("step_") and os.path.exists(
                    os.path.join(self.root, name, "meta.json")):
                try:
                    out.append(int(name[len("step_"):]))
                except ValueError:
                    pass
        return sorted(out)

    def load(self, step: int) -> Tuple[dict, Dict[str, np.ndarray]]:
        d = self._dir(step)
        with open(os.path.join(d, "meta.json")) as f:
            meta = json.load(f)
        with np.load(os.path.join(d, "batch.npz")) as z:
            arrays = {k: z[k] for k in z.files}
        return meta, arrays
