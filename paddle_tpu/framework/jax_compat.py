"""Version gates for jax APIs that moved between releases.

The tree targets the current jax surface (top-level ``jax.shard_map`` with the
``axis_names=`` kwarg); older jax (< 0.5) only ships
``jax.experimental.shard_map.shard_map`` with the complementary ``auto=``
kwarg (axes NOT named manual). This shim presents the new calling convention
on either version so kernel/distributed code is written once. No new
dependencies — gating only, per the container contract.

Resolution order (``_resolve_shard_map``): ``jax.shard_map`` when present
(the promoted API — used as-is), else ``jax.experimental.shard_map`` wrapped
by ``_wrap_legacy_shard_map`` to translate the new kwargs. Both orders are
unit-tested by injection (tests/test_comm_audit.py), so a jax upgrade that
moves the symbol flips the resolver, not the callers.
"""

from __future__ import annotations

import importlib

import jax

__all__ = ["shard_map"]


def _wrap_legacy_shard_map(legacy):
    """Adapt the experimental signature to the promoted one: translate
    ``axis_names=`` (axes f IS manual over) to the complementary ``auto=``
    (axes left automatic) and ``check_vma=`` to its old name
    ``check_rep=``."""

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  axis_names=None, **kwargs):
        auto = None
        if axis_names is not None:
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
            kwargs["auto"] = auto
        if "check_vma" in kwargs:  # renamed from check_rep
            kwargs["check_rep"] = kwargs.pop("check_vma")
        mapped = legacy(f, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, **kwargs)
        if auto:
            # old experimental shard_map supports nonempty `auto` only under
            # jit (eager call raises NotImplementedError) — wrap it. The pp
            # schedules may still hit this jaxlib's "PartitionId unsupported"
            # wall at compile time on CPU; that limit is gated in tests.
            mapped = jax.jit(mapped)
        return mapped

    return shard_map


def _resolve_shard_map(jax_module=jax, import_module=importlib.import_module):
    """(shard_map callable, origin) for the given jax module: origin is
    ``"jax"`` for the promoted top-level API (returned unwrapped) or
    ``"experimental"`` for the legacy location (returned wrapped).
    Injectable for tests; raises ImportError naming both probed paths if
    neither resolves."""
    fn = getattr(jax_module, "shard_map", None)
    if fn is not None:
        return fn, "jax"
    try:
        legacy = import_module("jax.experimental.shard_map").shard_map
    except (ImportError, AttributeError) as e:
        raise ImportError(
            "no shard_map found: neither jax.shard_map nor "
            "jax.experimental.shard_map.shard_map resolved") from e
    return _wrap_legacy_shard_map(legacy), "experimental"


shard_map, _SHARD_MAP_ORIGIN = _resolve_shard_map()
