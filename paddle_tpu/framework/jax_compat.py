"""Version gates for jax APIs that moved between releases.

The tree targets the current jax surface (top-level ``jax.shard_map`` with the
``axis_names=`` kwarg); older jax (< 0.5) only ships
``jax.experimental.shard_map.shard_map`` with the complementary ``auto=``
kwarg (axes NOT named manual). This shim presents the new calling convention
on either version so kernel/distributed code is written once. No new
dependencies — gating only, per the container contract.
"""

from __future__ import annotations

import jax

__all__ = ["shard_map"]

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  axis_names=None, **kwargs):
        auto = None
        if axis_names is not None:
            # new API: `axis_names` = mesh axes f is manual over;
            # old API: `auto` = mesh axes left automatic — the complement
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
            kwargs["auto"] = auto
        if "check_vma" in kwargs:  # renamed from check_rep
            kwargs["check_rep"] = kwargs.pop("check_vma")
        mapped = _experimental_shard_map(f, mesh=mesh, in_specs=in_specs,
                                         out_specs=out_specs, **kwargs)
        if auto:
            # old experimental shard_map supports nonempty `auto` only under
            # jit (eager call raises NotImplementedError) — wrap it. The pp
            # schedules may still hit this jaxlib's "PartitionId unsupported"
            # wall at compile time on CPU; that limit is gated in tests.
            mapped = jax.jit(mapped)
        return mapped
