"""paddle_tpu.framework — ParamAttr, RNG state, save/load (reference: python/paddle/framework)."""

from __future__ import annotations

from .io import load, save  # noqa: F401
from .random import get_rng_state, next_key, rng_guard, seed, set_rng_state  # noqa: F401


class ParamAttr:
    """Reference: python/paddle/base/param_attr.py — parameter configuration bundle."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0, regularizer=None,
                 trainable=True, do_model_average=True, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip


def set_grad_enabled(mode):
    from ..core.autograd_engine import set_grad_enabled as _s

    return _s(mode)
