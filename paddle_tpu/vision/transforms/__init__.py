"""vision transforms (reference: python/paddle/vision/transforms) — numpy HWC pipeline."""

from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(np.asarray(img))


class ToTensor(BaseTransform):
    """HWC uint8 -> CHW float32 in [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        if img.ndim == 2:
            img = img[:, :, None]
        arr = img.astype(np.float32) / 255.0 if img.dtype == np.uint8 else img.astype(np.float32)
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return arr


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            m = self.mean.reshape(-1, 1, 1)
            s = self.std.reshape(-1, 1, 1)
        else:
            m = self.mean.reshape(1, 1, -1)
            s = self.std.reshape(1, 1, -1)
        return (img - m) / s


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        import jax

        chw = img.ndim == 3 and img.shape[0] in (1, 3) and img.shape[0] < img.shape[-1]
        arr = np.asarray(img, np.float32)
        if chw:
            new_shape = (arr.shape[0],) + self.size
        elif arr.ndim == 3:
            new_shape = self.size + (arr.shape[2],)
        else:
            new_shape = self.size
        out = jax.image.resize(arr, new_shape, method="linear")
        return np.asarray(out).astype(img.dtype if img.dtype != np.uint8 else np.float32)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return img[:, ::-1].copy() if img.ndim == 2 else np.flip(img, axis=-2 if img.shape[0] in (1, 3) else 1).copy()
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return np.flip(img, axis=0 if img.ndim == 2 else (1 if img.shape[0] in (1, 3) else 0)).copy()
        return img


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0, padding_mode="constant"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        chw = img.ndim == 3 and img.shape[0] in (1, 3)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        arr = img
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) else [self.padding] * 2
            width = [(0, 0)] * arr.ndim
            width[h_ax] = (p[0], p[0])
            width[w_ax] = (p[1] if len(p) > 1 else p[0],) * 2
            arr = np.pad(arr, width)
        th, tw = self.size
        h, w = arr.shape[h_ax], arr.shape[w_ax]
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        sl = [slice(None)] * arr.ndim
        sl[h_ax] = slice(i, i + th)
        sl[w_ax] = slice(j, j + tw)
        return arr[tuple(sl)]


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        chw = img.ndim == 3 and img.shape[0] in (1, 3)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        th, tw = self.size
        h, w = img.shape[h_ax], img.shape[w_ax]
        i, j = (h - th) // 2, (w - tw) // 2
        sl = [slice(None)] * img.ndim
        sl[h_ax] = slice(i, i + th)
        sl[w_ax] = slice(j, j + tw)
        return img[tuple(sl)]


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def _apply_image(self, img):
        if img.ndim == 2:
            img = img[:, :, None]
        return img.transpose(self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = 1 + np.random.uniform(-self.value, self.value)
        return np.clip(img.astype(np.float32) * f, 0, 255).astype(img.dtype)


def to_tensor(pic, data_format="CHW"):
    return Tensor(ToTensor(data_format)(pic))


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
    return Tensor(Normalize(mean, std, data_format)._apply_image(arr))


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(np.asarray(img))


def hflip(img):
    return np.flip(np.asarray(img), axis=-2).copy()


def center_crop(img, output_size):
    return CenterCrop(output_size)(np.asarray(img))
