"""vision transforms (reference: python/paddle/vision/transforms) — numpy HWC pipeline."""

from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(np.asarray(img))


class ToTensor(BaseTransform):
    """HWC uint8 -> CHW float32 in [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        if img.ndim == 2:
            img = img[:, :, None]
        arr = img.astype(np.float32) / 255.0 if img.dtype == np.uint8 else img.astype(np.float32)
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return arr


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            m = self.mean.reshape(-1, 1, 1)
            s = self.std.reshape(-1, 1, 1)
        else:
            m = self.mean.reshape(1, 1, -1)
            s = self.std.reshape(1, 1, -1)
        return (img - m) / s


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        import jax

        chw = img.ndim == 3 and img.shape[0] in (1, 3) and img.shape[0] < img.shape[-1]
        arr = np.asarray(img, np.float32)
        if chw:
            new_shape = (arr.shape[0],) + self.size
        elif arr.ndim == 3:
            new_shape = self.size + (arr.shape[2],)
        else:
            new_shape = self.size
        out = jax.image.resize(arr, new_shape, method="linear")
        return np.asarray(out).astype(img.dtype if img.dtype != np.uint8 else np.float32)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return img[:, ::-1].copy() if img.ndim == 2 else np.flip(img, axis=-2 if img.shape[0] in (1, 3) else 1).copy()
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return np.flip(img, axis=0 if img.ndim == 2 else (1 if img.shape[0] in (1, 3) else 0)).copy()
        return img


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0, padding_mode="constant"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        chw = img.ndim == 3 and img.shape[0] in (1, 3)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        arr = img
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) else [self.padding] * 2
            width = [(0, 0)] * arr.ndim
            width[h_ax] = (p[0], p[0])
            width[w_ax] = (p[1] if len(p) > 1 else p[0],) * 2
            arr = np.pad(arr, width)
        th, tw = self.size
        h, w = arr.shape[h_ax], arr.shape[w_ax]
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        sl = [slice(None)] * arr.ndim
        sl[h_ax] = slice(i, i + th)
        sl[w_ax] = slice(j, j + tw)
        return arr[tuple(sl)]


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        chw = img.ndim == 3 and img.shape[0] in (1, 3)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        th, tw = self.size
        h, w = img.shape[h_ax], img.shape[w_ax]
        i, j = (h - th) // 2, (w - tw) // 2
        sl = [slice(None)] * img.ndim
        sl[h_ax] = slice(i, i + th)
        sl[w_ax] = slice(j, j + tw)
        return img[tuple(sl)]


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def _apply_image(self, img):
        if img.ndim == 2:
            img = img[:, :, None]
        return img.transpose(self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = 1 + np.random.uniform(-self.value, self.value)
        return np.clip(img.astype(np.float32) * f, 0, 255).astype(img.dtype)


def to_tensor(pic, data_format="CHW"):
    return Tensor(ToTensor(data_format)(pic))


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img)
    return Tensor(Normalize(mean, std, data_format)._apply_image(arr))


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(np.asarray(img))


def hflip(img):
    return np.flip(np.asarray(img), axis=-2).copy()


def center_crop(img, output_size):
    return CenterCrop(output_size)(np.asarray(img))


# ---------------------------------------------------------------------------
# breadth completion (reference: vision/transforms/transforms.py + functional)
# ---------------------------------------------------------------------------

def _rng():
    from ...framework.random import next_host_seed

    return np.random.default_rng(next_host_seed())


def _as_hwc(img):
    """Normalize to HWC for the geometry ops; returns (arr, restore_fn).
    CHW detection mirrors RandomCrop/CenterCrop in this module."""
    arr = np.asarray(img)
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[2] not in (1, 3)
    if chw:
        return arr.transpose(1, 2, 0), lambda a: a.transpose(2, 0, 1)
    return arr, lambda a: a


def crop(img, top, left, height, width):
    arr, restore = _as_hwc(img)
    return restore(arr[top:top + height, left:left + width])


def vflip(img):
    arr, restore = _as_hwc(img)
    return restore(arr[::-1].copy())


def pad(img, padding, fill=0, padding_mode="constant"):
    if isinstance(padding, int):
        padding = [padding] * 4
    elif len(padding) == 2:  # (left/right, top/bottom) — reference API form
        padding = [padding[0], padding[1], padding[0], padding[1]]
    l, t, r, b = padding
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if padding_mode == "constant" else {}
    arr, restore = _as_hwc(img)
    cfg = [(t, b), (l, r)] + ([(0, 0)] if arr.ndim == 3 else [])
    return restore(np.pad(arr, cfg, mode=mode, **kw))


def adjust_brightness(img, brightness_factor):
    arr = np.asarray(img).astype(np.float32) * brightness_factor
    return np.clip(arr, 0, 255).astype(np.asarray(img).dtype)


def adjust_contrast(img, contrast_factor):
    arr = np.asarray(img).astype(np.float32)
    mean = arr.mean()
    out = mean + contrast_factor * (arr - mean)
    return np.clip(out, 0, 255).astype(np.asarray(img).dtype)


def adjust_saturation(img, saturation_factor):
    arr = np.asarray(img).astype(np.float32)
    gray = arr.mean(-1, keepdims=True) if arr.ndim == 3 else arr
    out = gray + saturation_factor * (arr - gray)
    return np.clip(out, 0, 255).astype(np.asarray(img).dtype)


def adjust_hue(img, hue_factor):
    """Rotate hue by hue_factor in [-0.5, 0.5] (HSV roundtrip)."""
    arr = np.asarray(img).astype(np.float32) / 255.0
    r, g, b = arr[..., 0], arr[..., 1], arr[..., 2]
    mx, mn = arr.max(-1), arr.min(-1)
    diff = mx - mn + 1e-8
    h = np.zeros_like(mx)
    h = np.where(mx == r, ((g - b) / diff) % 6, h)
    h = np.where(mx == g, (b - r) / diff + 2, h)
    h = np.where(mx == b, (r - g) / diff + 4, h)
    h = (h / 6.0 + hue_factor) % 1.0
    s = np.where(mx > 0, diff / (mx + 1e-8), 0)
    v = mx
    i = np.floor(h * 6)
    f = h * 6 - i
    p, q, t = v * (1 - s), v * (1 - f * s), v * (1 - (1 - f) * s)
    i = i.astype(np.int32) % 6
    out = np.stack([
        np.choose(i, [v, q, p, p, t, v]),
        np.choose(i, [t, v, v, q, p, p]),
        np.choose(i, [p, p, t, v, v, q])], -1)
    return np.clip(out * 255, 0, 255).astype(np.asarray(img).dtype)


def to_grayscale(img, num_output_channels=1):
    arr = np.asarray(img).astype(np.float32)
    gray = arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114
    out = np.repeat(gray[..., None], num_output_channels, -1)
    return out.astype(np.asarray(img).dtype)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotation via inverse-mapped nearest sampling (pure numpy).
    expand=True enlarges the canvas to hold the whole rotated image."""
    arr, restore = _as_hwc(img)
    h, w = arr.shape[:2]
    cy, cx = ((h - 1) / 2, (w - 1) / 2) if center is None else (center[1], center[0])
    rad = np.deg2rad(angle)
    cos, sin = np.cos(rad), np.sin(rad)
    if expand:
        oh = int(np.ceil(abs(h * cos) + abs(w * sin)))
        ow = int(np.ceil(abs(w * cos) + abs(h * sin)))
        ocy, ocx = (oh - 1) / 2, (ow - 1) / 2
    else:
        oh, ow, ocy, ocx = h, w, cy, cx
    yy, xx = np.meshgrid(np.arange(oh), np.arange(ow), indexing="ij")
    xs = cos * (xx - ocx) + sin * (yy - ocy) + cx
    ys = -sin * (xx - ocx) + cos * (yy - ocy) + cy
    xi = np.round(xs).astype(np.int64)
    yi = np.round(ys).astype(np.int64)
    valid = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
    out_shape = (oh, ow) + arr.shape[2:]
    out = np.full(out_shape, fill, arr.dtype)
    out[valid] = arr[yi[valid], xi[valid]]
    return restore(out)


def affine(img, angle=0.0, translate=(0, 0), scale=1.0, shear=(0.0, 0.0),
           interpolation="nearest", fill=0, center=None):
    """Inverse-mapped affine transform (rotation+translate+scale+shear)."""
    arr, restore = _as_hwc(img)
    h, w = arr.shape[:2]
    cy, cx = ((h - 1) / 2, (w - 1) / 2) if center is None else (center[1], center[0])
    rad = np.deg2rad(angle)
    sx, sy = np.deg2rad(shear[0]), np.deg2rad(shear[1])
    # forward matrix M = T(center+translate) R S Shear T(-center); invert it
    m00 = scale * (np.cos(rad) + np.tan(sy) * np.sin(rad))
    m01 = scale * (np.tan(sx) * np.cos(rad) + np.sin(rad))
    m10 = scale * (-np.sin(rad) + np.tan(sy) * np.cos(rad))
    m11 = scale * (-np.tan(sx) * np.sin(rad) + np.cos(rad))
    M = np.array([[m00, m01], [m10, m11]], np.float64)
    Minv = np.linalg.inv(M)
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    dx = xx - cx - translate[0]
    dy = yy - cy - translate[1]
    xs = Minv[0, 0] * dx + Minv[0, 1] * dy + cx
    ys = Minv[1, 0] * dx + Minv[1, 1] * dy + cy
    xi, yi = np.round(xs).astype(np.int64), np.round(ys).astype(np.int64)
    valid = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
    out = np.full_like(arr, fill)
    out[valid] = arr[yi[valid], xi[valid]]
    return restore(out)


def perspective(img, startpoints, endpoints, interpolation="nearest", fill=0):
    """4-point perspective warp via the homography solve."""
    arr, restore = _as_hwc(img)
    h, w = arr.shape[:2]
    A = []
    for (x, y), (u, v) in zip(endpoints, startpoints):
        A.append([x, y, 1, 0, 0, 0, -u * x, -u * y])
        A.append([0, 0, 0, x, y, 1, -v * x, -v * y])
    B = np.array([c for pt in startpoints for c in pt], np.float64)
    coef = np.linalg.lstsq(np.asarray(A, np.float64), B, rcond=None)[0]
    H = np.append(coef, 1.0).reshape(3, 3)
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    denom = H[2, 0] * xx + H[2, 1] * yy + H[2, 2]
    xs = (H[0, 0] * xx + H[0, 1] * yy + H[0, 2]) / denom
    ys = (H[1, 0] * xx + H[1, 1] * yy + H[1, 2]) / denom
    xi, yi = np.round(xs).astype(np.int64), np.round(ys).astype(np.int64)
    valid = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
    out = np.full_like(arr, fill)
    out[valid] = arr[yi[valid], xi[valid]]
    return restore(out)


def erase(img, i, j, h, w, v, inplace=False):
    arr, restore = _as_hwc(img)
    arr = arr if inplace else arr.copy()
    arr[i:i + h, j:j + w] = v
    return restore(arr)


class SaturationTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = _rng().uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_saturation(img, f)


class ContrastTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = _rng().uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_hue(img, _rng().uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.t = [BrightnessTransform(brightness), ContrastTransform(contrast),
                  SaturationTransform(saturation), HueTransform(hue)]

    def _apply_image(self, img):
        order = _rng().permutation(4)
        for i in order:
            img = self.t[i](img)
        return img


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding, self.fill, self.mode = padding, fill, padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.mode)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale, self.ratio = scale, ratio

    def _apply_image(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        rng = _rng()
        for _ in range(10):
            area = h * w * rng.uniform(*self.scale)
            ar = np.exp(rng.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            cw = int(round(np.sqrt(area * ar)))
            ch = int(round(np.sqrt(area / ar)))
            if cw <= w and ch <= h:
                top = rng.integers(0, h - ch + 1)
                left = rng.integers(0, w - cw + 1)
                return resize(crop(arr, top, left, ch, cw), self.size)
        return resize(arr, self.size)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0):
        self.degrees = ((-degrees, degrees) if isinstance(degrees, (int, float))
                        else tuple(degrees))
        self.kw = dict(interpolation=interpolation, expand=expand,
                       center=center, fill=fill)

    def _apply_image(self, img):
        return rotate(img, _rng().uniform(*self.degrees), **self.kw)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None):
        self.degrees = ((-degrees, degrees) if isinstance(degrees, (int, float))
                        else tuple(degrees))
        self.translate, self.scale_rng, self.shear = translate, scale, shear
        self.fill, self.center = fill, center

    def _apply_image(self, img):
        rng = _rng()
        h, w = np.asarray(img).shape[:2]
        angle = rng.uniform(*self.degrees)
        tr = ((rng.uniform(-self.translate[0], self.translate[0]) * w,
               rng.uniform(-self.translate[1], self.translate[1]) * h)
              if self.translate else (0, 0))
        sc = rng.uniform(*self.scale_rng) if self.scale_rng else 1.0
        if self.shear is None:
            sh = (0.0, 0.0)
        elif isinstance(self.shear, (int, float)):
            sh = (rng.uniform(-self.shear, self.shear), 0.0)
        else:  # (min, max) range, or (xmin, xmax, ymin, ymax)
            vals = list(self.shear)
            sh_x = rng.uniform(vals[0], vals[1])
            sh_y = rng.uniform(vals[2], vals[3]) if len(vals) == 4 else 0.0
            sh = (sh_x, sh_y)
        return affine(img, angle, tr, sc, sh, fill=self.fill,
                      center=self.center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0):
        self.prob, self.d = prob, distortion_scale

    def _apply_image(self, img):
        rng = _rng()
        if rng.uniform() > self.prob:
            return np.asarray(img)
        h, w = np.asarray(img).shape[:2]
        dx, dy = self.d * w / 2, self.d * h / 2
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [(rng.uniform(0, dx), rng.uniform(0, dy)),
               (w - 1 - rng.uniform(0, dx), rng.uniform(0, dy)),
               (w - 1 - rng.uniform(0, dx), h - 1 - rng.uniform(0, dy)),
               (rng.uniform(0, dx), h - 1 - rng.uniform(0, dy))]
        return perspective(img, start, end)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1):
        self.n = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.n)


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False):
        self.prob, self.scale, self.ratio, self.value = prob, scale, ratio, value

    def _apply_image(self, img):
        rng = _rng()
        arr = np.asarray(img)
        if rng.uniform() > self.prob:
            return arr
        h, w = arr.shape[:2]
        for _ in range(10):
            area = h * w * rng.uniform(*self.scale)
            ar = np.exp(rng.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            eh, ew = int(round(np.sqrt(area / ar))), int(round(np.sqrt(area * ar)))
            if eh < h and ew < w:
                top = rng.integers(0, h - eh)
                left = rng.integers(0, w - ew)
                return erase(arr, top, left, eh, ew, self.value)
        return arr
