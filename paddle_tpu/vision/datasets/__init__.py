"""vision datasets (reference: python/paddle/vision/datasets).

Zero-egress environment: dataset classes accept a local ``data_file``; when absent
they generate a deterministic synthetic split with the real schema/shapes so training
pipelines and benchmarks run hermetically (mirrors the reference tests' use of fake
data readers).
"""

from __future__ import annotations

import numpy as np

from ...io.dataset import Dataset


class _SyntheticImageDataset(Dataset):
    IMAGE_SHAPE = (3, 32, 32)
    NUM_CLASSES = 10
    SIZE = {"train": 50000, "test": 10000}

    def __init__(self, mode="train", transform=None, backend="cv2", size=None, seed=0):
        self.mode = mode
        self.transform = transform
        self.n = size or min(self.SIZE.get(mode, 1024), 2048)
        rng = np.random.RandomState(seed + (0 if mode == "train" else 1))
        c, h, w = self.IMAGE_SHAPE
        self.images = rng.randint(0, 256, (self.n, h, w, c), dtype=np.uint8)
        self.labels = rng.randint(0, self.NUM_CLASSES, (self.n,), dtype=np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return self.n


class Cifar10(_SyntheticImageDataset):
    IMAGE_SHAPE = (3, 32, 32)
    NUM_CLASSES = 10


class Cifar100(_SyntheticImageDataset):
    IMAGE_SHAPE = (3, 32, 32)
    NUM_CLASSES = 100


class MNIST(_SyntheticImageDataset):
    IMAGE_SHAPE = (1, 28, 28)
    NUM_CLASSES = 10

    def __init__(self, mode="train", transform=None, image_path=None, label_path=None, backend=None, size=None, seed=0):
        super().__init__(mode, transform, size=size, seed=seed)


class FashionMNIST(MNIST):
    pass


class Flowers(_SyntheticImageDataset):
    IMAGE_SHAPE = (3, 224, 224)
    NUM_CLASSES = 102
    SIZE = {"train": 1020, "test": 1020, "valid": 1020}


class VOC2012(_SyntheticImageDataset):
    IMAGE_SHAPE = (3, 224, 224)
    NUM_CLASSES = 21
    SIZE = {"train": 512, "test": 128}


class DatasetFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None, is_valid_file=None):
        import os

        self.root = root
        self.transform = transform
        classes = sorted(d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                self.samples.append((os.path.join(cdir, fname), self.class_to_idx[c]))

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = np.load(path) if path.endswith(".npy") else np.asarray(_load_image(path))
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


ImageFolder = DatasetFolder


def _load_image(path):
    try:
        from PIL import Image

        return Image.open(path).convert("RGB")
    except ImportError as e:
        raise RuntimeError("PIL unavailable; use .npy images with DatasetFolder") from e
