"""Vision Transformer (bench config #5: ViT-L through the compiler path).

Reference anchor: python/paddle/vision ships CNN zoos; ViT is the
transformer-vision member the benchmarks call for. Pre-LN encoder, learned
positions, cls token. Same logical-axis convention as models/llama."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...distributed.auto_parallel.logical_sharding import annotate, constrain
from ...nn import initializer as I
from ...nn.layer.layers import Layer, LayerList

__all__ = ["VisionTransformer", "ViTConfig", "vit_b_16", "vit_l_16"]


class ViTConfig:
    def __init__(self, image_size=224, patch_size=16, in_channels=3,
                 hidden_size=768, num_layers=12, num_heads=12, mlp_ratio=4.0,
                 num_classes=1000, dropout=0.0, dtype="float32",
                 recompute=False):
        self.image_size = image_size
        self.patch_size = patch_size
        self.in_channels = in_channels
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.mlp_ratio = mlp_ratio
        self.num_classes = num_classes
        self.dropout = dropout
        self.dtype = dtype
        self.recompute = recompute

    @property
    def num_patches(self):
        return (self.image_size // self.patch_size) ** 2

    @classmethod
    def tiny(cls, **over):
        d = dict(image_size=32, patch_size=8, hidden_size=64, num_layers=2,
                 num_heads=4, num_classes=10)
        d.update(over)
        return cls(**d)


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


class ViTBlock(Layer):
    """Pre-LN transformer block."""

    def __init__(self, cfg: ViTConfig):
        super().__init__()
        h = cfg.hidden_size
        m = int(h * cfg.mlp_ratio)
        self.num_heads = cfg.num_heads
        init = I.TruncatedNormal(std=0.02)
        mk = lambda shape, ini=init: self.create_parameter(
            shape, dtype=cfg.dtype, default_initializer=ini)
        self.ln1_w = mk([h], I.Constant(1.0))
        self.ln1_b = mk([h], I.Constant(0.0))
        self.qkv_w = annotate(mk([h, 3 * h]), "embed", "heads")
        self.qkv_b = mk([3 * h], I.Constant(0.0))
        self.proj_w = annotate(mk([h, h]), "heads", "embed")
        self.proj_b = mk([h], I.Constant(0.0))
        self.ln2_w = mk([h], I.Constant(1.0))
        self.ln2_b = mk([h], I.Constant(0.0))
        self.fc1_w = annotate(mk([h, m]), "embed", "mlp")
        self.fc1_b = mk([m], I.Constant(0.0))
        self.fc2_w = annotate(mk([m, h]), "mlp", "embed")
        self.fc2_b = mk([h], I.Constant(0.0))

    def _ln(self, x, w, b):
        xf = x.astype(jnp.float32)
        mu = xf.mean(-1, keepdims=True)
        var = xf.var(-1, keepdims=True)
        return ((xf - mu) * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * w + b

    def forward(self, x):
        x = _unwrap(x)
        b, n, h = x.shape
        nh = self.num_heads
        hd = h // nh
        y = self._ln(x, self.ln1_w._data, self.ln1_b._data)
        qkv = jnp.matmul(y, self.qkv_w._data) + self.qkv_b._data
        q, k, v = jnp.split(qkv.reshape(b, n, 3, nh, hd), 3, axis=2)
        q, k, v = q[:, :, 0], k[:, :, 0], v[:, :, 0]
        from ...nn.functional.flash_attention import _xla_attention

        attn = _xla_attention(q, k, v, causal=False).reshape(b, n, h)
        x = x + jnp.matmul(attn, self.proj_w._data) + self.proj_b._data
        y = self._ln(x, self.ln2_w._data, self.ln2_b._data)
        y = jax.nn.gelu(jnp.matmul(y, self.fc1_w._data) + self.fc1_b._data)
        y = constrain(y, "batch", None, "mlp")
        x = x + jnp.matmul(y, self.fc2_w._data) + self.fc2_b._data
        return constrain(x, "batch", None, "embed")


class VisionTransformer(Layer):
    def __init__(self, cfg: ViTConfig):
        super().__init__()
        self.config = cfg
        h, p, c = cfg.hidden_size, cfg.patch_size, cfg.in_channels
        init = I.TruncatedNormal(std=0.02)
        self.patch_w = annotate(self.create_parameter(
            [p * p * c, h], dtype=cfg.dtype, default_initializer=init),
            None, "embed")
        self.patch_b = self.create_parameter([h], dtype=cfg.dtype,
                                             default_initializer=I.Constant(0.0))
        self.cls_token = self.create_parameter([1, 1, h], dtype=cfg.dtype,
                                               default_initializer=init)
        self.pos_embed = self.create_parameter(
            [1, cfg.num_patches + 1, h], dtype=cfg.dtype,
            default_initializer=init)
        self.blocks = LayerList([ViTBlock(cfg) for _ in range(cfg.num_layers)])
        self.ln_w = self.create_parameter([h], default_initializer=I.Constant(1.0), dtype=cfg.dtype)
        self.ln_b = self.create_parameter([h], default_initializer=I.Constant(0.0), dtype=cfg.dtype)
        self.head_w = self.create_parameter([h, cfg.num_classes], dtype=cfg.dtype,
                                            default_initializer=init)
        self.head_b = self.create_parameter([cfg.num_classes], dtype=cfg.dtype,
                                            default_initializer=I.Constant(0.0))

    def _patchify(self, img):
        """[b, c, H, W] -> [b, n_patches, p*p*c] without conv: a reshape the
        MXU-bound matmul consumes directly."""
        b, c, H, W = img.shape
        p = self.config.patch_size
        img = img.reshape(b, c, H // p, p, W // p, p)
        img = img.transpose(0, 2, 4, 3, 5, 1)  # b, hp, wp, p, p, c
        return img.reshape(b, (H // p) * (W // p), p * p * c)

    def forward(self, images):
        x = _unwrap(images)
        x = self._patchify(x)
        x = jnp.matmul(x, self.patch_w._data) + self.patch_b._data
        b = x.shape[0]
        cls = jnp.broadcast_to(self.cls_token._data, (b, 1, x.shape[-1]))
        x = jnp.concatenate([cls, x], axis=1) + self.pos_embed._data
        x = constrain(x, "batch", None, "embed")
        for blk in self.blocks:
            if self.config.recompute and self.training:
                x = jax.checkpoint(lambda a, _l=blk: _unwrap(_l(a)))(x)
            else:
                x = _unwrap(blk(x))
        xf = x.astype(jnp.float32)
        mu = xf.mean(-1, keepdims=True)
        var = xf.var(-1, keepdims=True)
        x = ((xf - mu) * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype)
        x = x * self.ln_w._data + self.ln_b._data
        return jnp.matmul(x[:, 0], self.head_w._data) + self.head_b._data

    def loss_fn(self, images, labels):
        logits = _unwrap(self.forward(images)).astype(jnp.float32)
        lbl = _unwrap(labels)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(logp, lbl[..., None], axis=-1).mean()


def vit_b_16(**over):
    return VisionTransformer(ViTConfig(hidden_size=768, num_layers=12,
                                       num_heads=12, **over))


def vit_l_16(**over):
    return VisionTransformer(ViTConfig(hidden_size=1024, num_layers=24,
                                       num_heads=16, **over))
