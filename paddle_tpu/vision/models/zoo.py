"""CNN zoo breadth (reference: python/paddle/vision/models/ — vgg.py,
mobilenetv1.py, mobilenetv2.py, alexnet.py, squeezenet.py). Compact
implementations over the framework conv/norm/pool layers."""

from __future__ import annotations

from ... import nn

__all__ = ["VGG", "vgg11", "vgg13", "vgg16", "vgg19", "MobileNetV1",
           "mobilenet_v1", "MobileNetV2", "mobilenet_v2", "AlexNet",
           "alexnet", "SqueezeNet", "squeezenet1_0", "squeezenet1_1"]


# ---------------------------------------------------------------------------
# VGG
# ---------------------------------------------------------------------------

_VGG_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
          512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
          512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


class VGG(nn.Layer):
    def __init__(self, features, num_classes=1000, with_pool=True):
        super().__init__()
        self.features = features
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D([7, 7])
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(512 * 7 * 7, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, num_classes))
        self.num_classes = num_classes

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from ...tensor import flatten

            x = flatten(x, 1)
            x = self.classifier(x)
        return x


def _vgg_features(cfg, batch_norm=False):
    layers, cin = [], 3
    for v in cfg:
        if v == "M":
            layers.append(nn.MaxPool2D(2, 2))
        else:
            layers.append(nn.Conv2D(cin, v, 3, padding=1))
            if batch_norm:
                layers.append(nn.BatchNorm2D(v))
            layers.append(nn.ReLU())
            cin = v
    return nn.Sequential(*layers)


def _vgg(cfg_key, batch_norm=False, **kwargs):
    return VGG(_vgg_features(_VGG_CFGS[cfg_key], batch_norm), **kwargs)


def vgg11(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("A", batch_norm, **kwargs)


def vgg13(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("B", batch_norm, **kwargs)


def vgg16(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("D", batch_norm, **kwargs)


def vgg19(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("E", batch_norm, **kwargs)


# ---------------------------------------------------------------------------
# MobileNet v1/v2
# ---------------------------------------------------------------------------

def _conv_bn(cin, cout, k, stride=1, padding=0, groups=1):
    return nn.Sequential(
        nn.Conv2D(cin, cout, k, stride=stride, padding=padding, groups=groups,
                  bias_attr=False),
        nn.BatchNorm2D(cout), nn.ReLU6())


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        c = lambda ch: max(int(ch * scale), 8)
        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + [
              (512, 1024, 2), (1024, 1024, 1)]
        layers = [_conv_bn(3, c(32), 3, 2, 1)]
        for cin, cout, s in cfg:
            layers.append(_conv_bn(c(cin), c(cin), 3, s, 1, groups=c(cin)))
            layers.append(_conv_bn(c(cin), c(cout), 1))
        self.features = nn.Sequential(*layers)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...tensor import flatten

            x = self.fc(flatten(x, 1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)


class _InvertedResidual(nn.Layer):
    def __init__(self, cin, cout, stride, expand):
        super().__init__()
        hidden = int(round(cin * expand))
        self.use_res = stride == 1 and cin == cout
        layers = []
        if expand != 1:
            layers.append(_conv_bn(cin, hidden, 1))
        layers += [
            _conv_bn(hidden, hidden, 3, stride, 1, groups=hidden),
            nn.Conv2D(hidden, cout, 1, bias_attr=False),
            nn.BatchNorm2D(cout),
        ]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        cfgs = [  # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        c = lambda ch: max(int(ch * scale), 8)
        layers = [_conv_bn(3, c(32), 3, 2, 1)]
        cin = c(32)
        for t, ch, n, s in cfgs:
            for i in range(n):
                layers.append(_InvertedResidual(cin, c(ch),
                                                s if i == 0 else 1, t))
                cin = c(ch)
        self.last_ch = c(1280) if scale > 1.0 else 1280
        layers.append(_conv_bn(cin, self.last_ch, 1))
        self.features = nn.Sequential(*layers)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(nn.Dropout(0.2),
                                            nn.Linear(self.last_ch, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...tensor import flatten

            x = self.classifier(flatten(x, 1))
        return x


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)


# ---------------------------------------------------------------------------
# AlexNet / SqueezeNet
# ---------------------------------------------------------------------------

class AlexNet(nn.Layer):
    def __init__(self, num_classes=1000):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(), nn.MaxPool2D(3, 2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(), nn.MaxPool2D(3, 2))
        self.avgpool = nn.AdaptiveAvgPool2D([6, 6])
        self.classifier = nn.Sequential(
            nn.Dropout(), nn.Linear(256 * 36, 4096), nn.ReLU(),
            nn.Dropout(), nn.Linear(4096, 4096), nn.ReLU(),
            nn.Linear(4096, num_classes))

    def forward(self, x):
        from ...tensor import flatten

        x = self.avgpool(self.features(x))
        return self.classifier(flatten(x, 1))


def alexnet(pretrained=False, **kwargs):
    return AlexNet(**kwargs)


class _Fire(nn.Layer):
    def __init__(self, cin, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Sequential(nn.Conv2D(cin, squeeze, 1), nn.ReLU())
        self.e1 = nn.Sequential(nn.Conv2D(squeeze, e1, 1), nn.ReLU())
        self.e3 = nn.Sequential(nn.Conv2D(squeeze, e3, 3, padding=1), nn.ReLU())

    def forward(self, x):
        from ...tensor import concat

        s = self.squeeze(x)
        return concat([self.e1(s), self.e3(s)], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000):
        super().__init__()
        if version == "1.0":
            self.features = nn.Sequential(
                nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(), nn.MaxPool2D(3, 2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), nn.MaxPool2D(3, 2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                nn.MaxPool2D(3, 2), _Fire(512, 64, 256, 256))
        else:
            self.features = nn.Sequential(
                nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(), nn.MaxPool2D(3, 2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                nn.MaxPool2D(3, 2), _Fire(128, 32, 128, 128),
                _Fire(256, 32, 128, 128), nn.MaxPool2D(3, 2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        self.classifier = nn.Sequential(
            nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU(),
            nn.AdaptiveAvgPool2D(1))

    def forward(self, x):
        from ...tensor import flatten

        return flatten(self.classifier(self.features(x)), 1)


def squeezenet1_0(pretrained=False, **kwargs):
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return SqueezeNet("1.1", **kwargs)
