"""vision models (reference: python/paddle/vision/models)."""

from .lenet import LeNet  # noqa: F401
from .resnet import ResNet, resnet18, resnet34, resnet50, resnet101, resnet152  # noqa: F401
from .vit import VisionTransformer, ViTConfig, vit_b_16, vit_l_16  # noqa: F401
from .zoo import (  # noqa: F401
    AlexNet,
    MobileNetV1,
    MobileNetV2,
    SqueezeNet,
    VGG,
    alexnet,
    mobilenet_v1,
    mobilenet_v2,
    squeezenet1_0,
    squeezenet1_1,
    vgg11,
    vgg13,
    vgg16,
    vgg19,
)
