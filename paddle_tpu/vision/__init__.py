"""paddle_tpu.vision (reference: python/paddle/vision)."""

from . import datasets, models, transforms  # noqa: F401
from .models.resnet import ResNet, resnet18, resnet34, resnet50, resnet101, resnet152  # noqa: F401


def set_image_backend(backend):
    pass


def get_image_backend():
    return "numpy"
