"""Process bootstrap + DataParallel (reference: python/paddle/distributed/parallel.py).

TPU-native bootstrap: ``init_parallel_env`` maps to ``jax.distributed.initialize``
(coordination service = the TCPStore analogue, phi/core/distributed/store/tcp_store.h);
single-host SPMD needs no bootstrap at all — all local chips are visible to one
controller and collectives ride ICI via XLA.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from .communication.group import Group, get_default_group, new_group

_parallel_env_initialized = [False]


class ParallelEnv:
    """Reference: parallel.py ParallelEnv — env-var view of the launch contract."""

    @property
    def rank(self):
        v = os.environ.get("PADDLE_TRAINER_ID")
        # lazy fallback: querying jax initializes the XLA backend, which must
        # not happen before jax.distributed.initialize in multi-host bootstrap
        return int(v) if v is not None else jax.process_index()

    @property
    def world_size(self):
        v = os.environ.get("PADDLE_TRAINERS_NUM")
        return int(v) if v is not None else jax.process_count()

    @property
    def local_rank(self):
        return int(os.environ.get("PADDLE_RANK_IN_NODE", 0))

    @property
    def device_id(self):
        return 0

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:0")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else []


def init_parallel_env():
    """Initialize multi-host JAX if the launch env asks for it; idempotent.

    ORDER MATTERS: jax.distributed.initialize must run before ANY backend
    query (jax.devices/process_count initialize XLA), so the decision is made
    purely from the PADDLE_* / MASTER_* launch env contract
    (reference: parallel.py:978 init_parallel_env + launch controllers)."""
    if _parallel_env_initialized[0]:
        return get_default_group()
    world = os.environ.get("PADDLE_TRAINERS_NUM")
    rank = os.environ.get("PADDLE_TRAINER_ID")
    coord = os.environ.get("MASTER_ADDR"), os.environ.get("MASTER_PORT")
    # idempotence without touching the backend: jax.distributed keeps its
    # client in global_state — if a launcher already called initialize(),
    # calling again would raise
    already = getattr(jax._src.distributed.global_state, "client", None)
    if (already is None and world is not None and int(world) > 1
            and rank is not None and all(coord)):
        jax.distributed.initialize(
            coordinator_address=f"{coord[0]}:{coord[1]}",
            num_processes=int(world),
            process_id=int(rank),
        )
    _parallel_env_initialized[0] = True
    return get_default_group()


def get_rank(group: Optional[Group] = None) -> int:
    """Controller rank (multi-host) — in single-controller SPMD there is one process."""
    if group is not None:
        return group.get_group_rank(jax.process_index())
    return jax.process_index()


def get_world_size(group: Optional[Group] = None) -> int:
    if group is not None:
        return group.nranks
    return jax.process_count() if jax.process_count() > 1 else 1


def is_initialized() -> bool:
    return _parallel_env_initialized[0]


class DataParallel(Layer):
    """Reference: parallel.py:219. TPU-native DP = shard the batch over a mesh axis
    and let GSPMD insert the gradient all-reduce — the EagerReducer's bucketing +
    overlapped NCCL allreduce (collective/reducer.h:88) is subsumed by the XLA
    latency-hiding scheduler, which overlaps the reduce-scatter/all-gather with
    backward compute automatically."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25, last_comm_buffer_size=1,
                 find_unused_parameters=False, group=None):
        super().__init__()
        self._layers = layers
        self.group = group
        self.find_unused_parameters = find_unused_parameters
        n = jax.device_count()
        if n > 1:
            mesh = jax.sharding.Mesh(np.array(jax.devices()), ("dp",))
            self._dp_sharding = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("dp"))
            self._rep_sharding = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
            # params replicated across dp
            for p in layers.parameters():
                if not isinstance(p._data, jax.core.Tracer):
                    p._data = jax.device_put(p._data, self._rep_sharding)
        else:
            self._dp_sharding = None

    def forward(self, *inputs, **kwargs):
        if self._dp_sharding is not None:
            new_inputs = []
            for x in inputs:
                if isinstance(x, Tensor) and x.ndim > 0 and x.shape[0] % jax.device_count() == 0 \
                        and not isinstance(x._data, jax.core.Tracer):
                    x = Tensor(jax.device_put(x._data, self._dp_sharding), stop_gradient=x.stop_gradient)
                new_inputs.append(x)
            inputs = tuple(new_inputs)
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def scale_loss(self, loss):
        return loss

    @classmethod
    def no_sync(cls):
        import contextlib

        return contextlib.nullcontext()


def _spawn_worker(func, args, rank, nprocs, master_port):
    os.environ.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(nprocs),
        "PADDLE_RANK_IN_NODE": str(rank),
        "MASTER_ADDR": "127.0.0.1",
        "MASTER_PORT": str(master_port),
    })
    func(*args)


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Reference: spawn.py:463. On TPU SPMD one controller drives all local
    chips, so ``nprocs<=1`` is a direct call (the common case). ``nprocs>1``
    forks real worker processes with the trainer env contract — used by
    CPU-backend multi-process tests and by per-host multi-controller setups
    (each worker must then select a disjoint device set)."""
    if nprocs in (-1, 0, 1):
        func(*args)
        return None
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    master_port = options.get("master_port", 61700)
    procs = []
    for rank in range(nprocs):
        p = ctx.Process(target=_spawn_worker,
                        args=(func, args, rank, nprocs, master_port),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if not join:
        return procs
    for p in procs:
        p.join()
    bad = [p.exitcode for p in procs if p.exitcode != 0]
    if bad:
        raise RuntimeError(f"spawn workers failed with exit codes {bad}")
    return None
