"""paddle_tpu.distributed.launch — multi-process / multi-host job launcher.

Reference: python/paddle/distributed/launch (main.py:23, controllers/collective.py:22,
controllers/master.py:73,186). Usage::

    python -m paddle_tpu.distributed.launch --nproc_per_node 4 train.py --lr 1e-3
    python -m paddle_tpu.distributed.launch --master 10.0.0.1:6170 --nnodes 2 train.py
    python -m paddle_tpu.distributed.launch --master 10.0.0.1:6170 --nnodes 2:4 \
        --elastic_level 1 train.py          # elastic: min 2, max 4 nodes

TPU-native notes: on TPU pods one process per host drives all local chips
(SPMD), so ``--nproc_per_node`` defaults to 1 there; the rendezvous master is
the native TCPStore daemon (paddle_tpu/native/src/tcp_store.cc) rather than
etcd, and workers get the standard env contract (PADDLE_TRAINER_ID /
PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS / MASTER_ADDR / MASTER_PORT)
consumed by ``init_parallel_env`` → ``jax.distributed.initialize``.
"""

from .main import launch, main  # noqa: F401
