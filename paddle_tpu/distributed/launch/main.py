"""CLI entry: ``python -m paddle_tpu.distributed.launch`` (reference launch/main.py:23)."""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from .controllers import (CollectiveController, CollectiveElasticController,
                          Context, LaunchArgs)


def _parse(argv: List[str]) -> LaunchArgs:
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="Launch a distributed paddle_tpu job.")
    p.add_argument("--master", default=os.environ.get("PADDLE_MASTER"),
                   help="rendezvous store endpoint host:port (TCPStore)")
    p.add_argument("--nnodes", default=os.environ.get("PADDLE_NNODES", "1"),
                   help="node count N, or min:max for elastic jobs")
    p.add_argument("--nproc_per_node", type=int,
                   default=None, help="worker processes per node (TPU default: 1)")
    p.add_argument("--job_id", default=os.environ.get("PADDLE_JOB_ID", "default"))
    p.add_argument("--log_dir", default="log")
    p.add_argument("--devices", default=None,
                   help="visible device ids for workers (PADDLE_DEVICES)")
    p.add_argument("--elastic_level", type=int,
                   default=int(os.environ.get("PADDLE_ELASTIC_LEVEL", "0")),
                   help="max elastic restarts (0 = elastic off)")
    p.add_argument("--elastic_timeout", type=float, default=30.0)
    p.add_argument("-m", "--module", action="store_true", dest="run_module",
                   help="run script as a python module")
    p.add_argument("script", help="training script (or module with -m)")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    ns = p.parse_args(argv)
    return LaunchArgs(
        script=ns.script, script_args=ns.script_args, master=ns.master,
        nnodes=str(ns.nnodes), nproc_per_node=ns.nproc_per_node,
        job_id=ns.job_id, log_dir=ns.log_dir, devices=ns.devices,
        elastic_level=ns.elastic_level, elastic_timeout=ns.elastic_timeout,
        run_module=ns.run_module)


def launch(args: LaunchArgs) -> int:
    """Programmatic entry; returns the job exit code."""
    ctx = Context(args)
    elastic = args.elastic_level > 0 or ":" in args.nnodes
    ctrl = CollectiveElasticController(ctx) if elastic else CollectiveController(ctx)
    return ctrl.run()


def main(argv: Optional[List[str]] = None) -> int:
    return launch(_parse(sys.argv[1:] if argv is None else argv))


if __name__ == "__main__":
    sys.exit(main())
