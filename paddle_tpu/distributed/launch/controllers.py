"""Launch controllers: collective rendezvous + worker lifecycle (+ elastic).

Reference: python/paddle/distributed/launch/controllers/collective.py:22
(CollectiveController/CollectiveElasticController) and controllers/master.py
(HTTPMaster/ETCDMaster). The master here is the native TCPStore; rendezvous is
add/wait_ge on job keys, peer liveness is heartbeat keys scanned by the
watcher (launch/job/watcher in the reference).
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time
import uuid
from dataclasses import dataclass, field
from typing import List, Optional

from ..communication.store import TCPStore


def _local_ip() -> str:
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("8.8.8.8", 53))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"


@dataclass
class LaunchArgs:
    script: str = ""
    script_args: List[str] = field(default_factory=list)
    master: Optional[str] = None          # "host:port" of the rendezvous store
    nnodes: str = "1"                     # "N" or "min:max" (elastic)
    nproc_per_node: Optional[int] = None
    job_id: str = "default"
    log_dir: str = "log"
    elastic_level: int = 0                # 0 off, >0 max restarts
    elastic_timeout: float = 30.0
    heartbeat_interval: float = 3.0
    run_module: bool = False              # script is a module (python -m)
    devices: Optional[str] = None

    @property
    def min_nodes(self) -> int:
        return int(self.nnodes.split(":")[0])

    @property
    def max_nodes(self) -> int:
        parts = self.nnodes.split(":")
        return int(parts[-1])


class Context:
    """Runtime view of one launch invocation on this node."""

    def __init__(self, args: LaunchArgs):
        self.args = args
        self.node_ip = _local_ip()
        if args.nproc_per_node is None:
            # One process drives all local chips on TPU (SPMD); CPU debug runs
            # honor PADDLE_NPROC_PER_NODE.
            args.nproc_per_node = int(os.environ.get("PADDLE_NPROC_PER_NODE", "1"))
        self.node_id = f"{self.node_ip}-{os.getpid()}-{uuid.uuid4().hex[:6]}"


class Procs:
    """Local worker process set with per-rank log files."""

    def __init__(self, log_dir: str):
        self.procs: List[subprocess.Popen] = []
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)

    def start(self, cmd: List[str], env: dict, rank: int) -> None:
        log_path = os.path.join(self.log_dir, f"workerlog.{rank}")
        out = open(log_path, "ab")
        p = subprocess.Popen(cmd, env=env, stdout=out if rank != 0 else None,
                             stderr=subprocess.STDOUT if rank != 0 else None)
        p._pt_log = log_path  # type: ignore[attr-defined]
        p._pt_rank = rank  # type: ignore[attr-defined]
        self.procs.append(p)

    def poll(self) -> Optional[int]:
        """First non-zero exit code, 0 when all exited cleanly, None if running."""
        codes = [p.poll() for p in self.procs]
        for c in codes:
            if c is not None and c != 0:
                return c
        if all(c == 0 for c in codes):
            return 0
        return None

    def terminate(self, grace: float = 10.0) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.time() + grace
        for p in self.procs:
            remain = max(0.1, deadline - time.time())
            try:
                p.wait(timeout=remain)
            except subprocess.TimeoutExpired:
                p.kill()
        self.procs.clear()


class CollectiveController:
    """Rendezvous all nodes at the master store, launch local workers, watch them.

    Store schema (per generation g):
      {job}/gen            — int, incremented on every (re)rendezvous
      {job}/g{g}/nodes     — arrival counter (add)
      {job}/g{g}/node/{i}  — json {ip, nproc, node_id}
      {job}/beat/{node_id} — heartbeat wall-clock (elastic only)
    """

    def __init__(self, ctx: Context):
        self.ctx = ctx
        self.args = ctx.args
        self.store: Optional[TCPStore] = None
        self.procs = Procs(self.args.log_dir)
        self.restarts = 0

    # -- rendezvous --------------------------------------------------------
    def _connect_store(self) -> Optional[TCPStore]:
        if self.args.master is None:
            return None
        host, port = self.args.master.rsplit(":", 1)
        # The node whose IP matches the master address hosts the daemon; binding
        # races are resolved by "bind wins, everyone else connects".
        is_master_host = host in ("127.0.0.1", "localhost", self.ctx.node_ip)
        store = None
        if is_master_host:
            try:
                store = TCPStore(host, int(port), is_master=True,
                                 world_size=self.args.min_nodes, timeout=300)
            except (RuntimeError, OSError):
                store = None  # someone else bound it first
        if store is None:
            store = TCPStore(host, int(port), is_master=False,
                             world_size=self.args.min_nodes, timeout=300)
        return store

    def rendezvous(self) -> dict:
        """Returns the job layout: ranks/endpoints for this generation."""
        args = self.args
        if self.store is None:
            self.store = self._connect_store()
        if self.store is None:  # single node, no master
            return {
                "gen": 0, "node_rank": 0, "nnodes": 1,
                "nodes": [{"ip": self.ctx.node_ip, "nproc": args.nproc_per_node,
                           "node_id": self.ctx.node_id}],
            }
        job = f"job/{args.job_id}"
        gen = self.store.add(f"{job}/gen_probe", 0)  # current value
        seq = self.store.add(f"{job}/g{gen}/nodes", 1) - 1
        self.store.set(f"{job}/g{gen}/node/{seq}", json.dumps({
            "ip": self.ctx.node_ip, "nproc": args.nproc_per_node,
            "node_id": self.ctx.node_id}).encode())
        self.store.wait_ge(f"{job}/g{gen}/nodes", args.min_nodes,
                           timeout=self.args.elastic_timeout if args.elastic_level
                           else 600.0)
        n = int(self.store.add(f"{job}/g{gen}/nodes", 0))
        n = min(n, args.max_nodes)
        nodes = [json.loads(self.store.get(f"{job}/g{gen}/node/{i}"))
                 for i in range(n)]
        return {"gen": gen, "node_rank": seq, "nnodes": n, "nodes": nodes}

    # -- workers -----------------------------------------------------------
    def launch_workers(self, layout: dict) -> None:
        args = self.args
        nodes = layout["nodes"]
        node_rank = layout["node_rank"]
        world = sum(nd["nproc"] for nd in nodes)
        base_rank = sum(nd["nproc"] for nd in nodes[:node_rank])
        endpoints = ",".join(
            f"{nd['ip']}:{61000 + i}" for nd in nodes for i in range(nd["nproc"]))
        master_ip = nodes[0]["ip"]
        coord_port = 62000 + (layout["gen"] % 1000)

        for local_rank in range(args.nproc_per_node):
            rank = base_rank + local_rank
            env = dict(os.environ)
            env.update({
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(world),
                "PADDLE_TRAINER_ENDPOINTS": endpoints,
                "PADDLE_CURRENT_ENDPOINT": endpoints.split(",")[rank],
                "PADDLE_RANK_IN_NODE": str(local_rank),
                "PADDLE_LOCAL_SIZE": str(args.nproc_per_node),
                "PADDLE_NNODES": str(layout["nnodes"]),
                "PADDLE_JOB_ID": args.job_id,
                "PADDLE_RESTART_NUM": str(self.restarts),
                "MASTER_ADDR": master_ip,
                "MASTER_PORT": str(coord_port),
            })
            if args.devices is not None:
                env["PADDLE_DEVICES"] = args.devices
            cmd = [sys.executable]
            if args.run_module:
                cmd += ["-m", args.script]
            else:
                cmd += [args.script]
            cmd += args.script_args
            self.procs.start(cmd, env, rank)

    # -- lifecycle ---------------------------------------------------------
    def run(self) -> int:
        layout = self.rendezvous()
        self.launch_workers(layout)
        return self.watch(layout)

    def watch(self, layout: dict) -> int:
        while True:
            code = self.procs.poll()
            if code is not None:
                if code != 0:
                    self.procs.terminate()
                self.stop()
                return code
            time.sleep(1.0)

    def stop(self):
        if self.store is not None:
            self.store.close()
            self.store = None


class CollectiveElasticController(CollectiveController):
    """Adds heartbeat + peer watch + relaunch-on-change (reference
    controllers/collective.py:262 + fleet/elastic/manager.py:125).

    Fault model: recovery = re-rendezvous + restart workers (training resumes
    from the last checkpoint); no in-run state migration — matching the
    reference's elastic semantics.
    """

    def run(self) -> int:
        from ..fleet.elastic import ElasticManager, ElasticStatus

        while True:
            layout = self.rendezvous()
            mgr = ElasticManager(
                store=self.store, job_id=self.args.job_id,
                node_id=self.ctx.node_id,
                expected=[nd["node_id"] for nd in layout["nodes"]],
                heartbeat_interval=self.args.heartbeat_interval,
                ttl=self.args.heartbeat_interval * 3)
            mgr.start()
            self.launch_workers(layout)
            try:
                status = self._watch_elastic(mgr)
            finally:
                mgr.stop()
            if status == ElasticStatus.COMPLETED:
                self.stop()
                return 0
            if status == ElasticStatus.ERROR or \
                    self.restarts >= max(self.args.elastic_level, 1):
                self.procs.terminate()
                self.stop()
                return 1
            # peer change → restart generation
            self.procs.terminate()
            self.restarts += 1
            if self.store is not None:
                self.store.add(f"job/{self.args.job_id}/gen_probe", 1)
            time.sleep(1.0)

    def _watch_elastic(self, mgr) -> "ElasticStatus":  # noqa: F821
        from ..fleet.elastic import ElasticStatus

        while True:
            code = self.procs.poll()
            if code == 0:
                return ElasticStatus.COMPLETED
            if code is not None:
                # local worker died → treat as restartable fault
                return ElasticStatus.RESTART
            if mgr.peers_changed():
                return ElasticStatus.RESTART
            time.sleep(1.0)
