"""Process groups over mesh axes.

Reference: fluid/distributed/collective/process_group.h + python collective.py:195.
TPU-native: a Group is a handle onto a named mesh axis (or a sub-mesh). Collectives
on a Group lower to XLA collective HLOs (psum/all_gather/...) over ICI when traced
under shard_map/pjit with that axis, and to shard_map-wrapped execution on global
arrays in eager mode. There is no communicator bootstrap (no NCCL ids): XLA owns
the fabric; TCPStore-style rendezvous exists only at jax.distributed init time.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import numpy as np


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    def __init__(self, ranks: List[int], gid: int = 0, axis_name: Optional[str] = None, mesh=None):
        self.ranks = list(ranks)
        self.id = gid
        self.axis_name = axis_name or f"group_{gid}"
        self.mesh = mesh
        self.pg = self  # parity: group.process_group

    @property
    def nranks(self):
        return len(self.ranks)

    @property
    def world_size(self):
        return len(self.ranks)

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    @property
    def rank(self):
        from ..parallel import get_rank

        return self.get_group_rank(get_rank())

    def __repr__(self):
        return f"Group(id={self.id}, axis={self.axis_name}, ranks={self.ranks})"


_group_counter = [0]
_groups = {}
_default_group: Optional[Group] = None


def _world_mesh():
    """Lazily build the default 1-D world mesh over all devices."""
    devs = jax.devices()
    return jax.sharding.Mesh(np.array(devs), ("world",))


def get_default_group() -> Group:
    global _default_group
    if _default_group is None:
        n = jax.device_count()
        _default_group = Group(list(range(n)), 0, axis_name="world", mesh=_world_mesh())
        _groups[0] = _default_group
    return _default_group


def new_group(ranks=None, backend=None, timeout=None, axis_name=None, mesh=None) -> Group:
    """Reference: collective.py:195. On TPU a group is a mesh-axis handle."""
    _group_counter[0] += 1
    gid = _group_counter[0]
    if ranks is None:
        ranks = list(range(jax.device_count()))
    g = Group(sorted(ranks), gid, axis_name=axis_name, mesh=mesh)
    _groups[gid] = g
    return g


def get_group(gid: int) -> Optional[Group]:
    return _groups.get(gid)


def destroy_process_group(group=None):
    global _default_group
    if group is None:
        _groups.clear()
        _default_group = None
    else:
        _groups.pop(group.id, None)


def is_initialized():
    return _default_group is not None
