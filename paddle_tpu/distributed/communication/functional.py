"""Functional collectives (reference: python/paddle/distributed/communication/*).

Two execution regimes, one API:

1. **Traced under shard_map/pjit** (how fleet engines run): ops lower to XLA
   collective HLOs over ICI — ``lax.psum`` / ``all_gather`` / ``psum_scatter`` /
   ``ppermute`` / ``all_to_all`` with the group's mesh-axis name. This replaces the
   reference's NCCLCommContext (phi/core/distributed/nccl_comm_context.h:40).

2. **Eager, single-controller SPMD**: a jax.Array is already the *global* logical
   tensor, so rank-local collective semantics degenerate: tensors are replicated
   across the group and the ops compute the equivalent replicated result
   (e.g. all_reduce(SUM) == x * nranks). This mirrors how the reference's tests use
   collectives on identical inputs, and keeps user code portable.

3. **Eager, multi-process** (after a multi-host ``init_parallel_env``): each
   controller holds genuinely different data, so ``all_reduce`` builds a global
   array with one shard per process and runs a jitted cross-process psum over
   the coordination-service-backed mesh — true per-rank semantics, matching the
   reference's per-rank collective tests (test_collective_api_base.py).
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ...core.tensor import Tensor, unwrap, wrap
from .group import Group, ReduceOp, get_default_group


def _axis_bound(axis_name) -> bool:
    """True iff axis_name is bound in the current trace (inside shard_map/pmap)."""
    try:
        lax.axis_index(axis_name)
        return True
    except NameError:
        return False
    except Exception:
        return False


def _group(group) -> Group:
    return group if group is not None else get_default_group()


def _task():
    class _Done:
        def wait(self):
            return None

        def is_completed(self):
            return True

    return _Done()


_mp_reduce_cache: dict = {}


def _mp_all_reduce(x, op, ranks):
    """True cross-process eager all-reduce over the processes in ``ranks``
    (rank == process_index, the init_parallel_env contract): one shard per
    member process on a mesh of exactly the group's devices; the reduce is a
    jitted psum. Only member processes execute the computation — jax
    multi-controller permits submesh computations as long as every process
    owning a shard calls in (same contract as a NCCL subgroup). Compiled fns
    are cached per (op, ranks, shape, dtype) — re-jitting each call would
    recompile every time."""
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    key = (str(op), tuple(ranks), tuple(x.shape), str(x.dtype))
    entry = _mp_reduce_cache.get(key)
    if entry is None:
        by_proc = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, d)
        devs = np.array([by_proc[p] for p in ranks])
        mesh = Mesh(devs, ("r",))

        def body(a):
            v = a[0]
            if op == ReduceOp.SUM:
                r = lax.psum(v, "r")
            elif op == ReduceOp.MAX:
                r = lax.pmax(v, "r")
            elif op == ReduceOp.MIN:
                r = lax.pmin(v, "r")
            elif op == ReduceOp.AVG:
                r = lax.pmean(v, "r")
            else:
                r = jnp.exp(lax.psum(jnp.log(v), "r"))
            return r[None]

        from ...framework.jax_compat import shard_map as _shard_map

        fn = jax.jit(_shard_map(body, mesh=mesh, in_specs=P("r"),
                                out_specs=P("r")))
        entry = (fn, mesh, by_proc[jax.process_index()], len(devs))
        _mp_reduce_cache[key] = entry
    fn, mesh, mine, n = entry
    shard = jax.device_put(x[None], mine)
    arr = jax.make_array_from_single_device_arrays(
        (n,) + x.shape, NamedSharding(mesh, P("r")), [shard])
    return fn(arr).addressable_shards[0].data[0]


def all_reduce(tensor: Tensor, op=ReduceOp.SUM, group=None, sync_op=True, use_calc_stream=False):
    g = _group(group)
    x = unwrap(tensor)
    if _axis_bound(g.axis_name):
        if op == ReduceOp.SUM:
            out = lax.psum(x, g.axis_name)
        elif op == ReduceOp.MAX:
            out = lax.pmax(x, g.axis_name)
        elif op == ReduceOp.MIN:
            out = lax.pmin(x, g.axis_name)
        elif op == ReduceOp.AVG:
            out = lax.pmean(x, g.axis_name)
        else:
            out = jnp.exp(lax.psum(jnp.log(x), g.axis_name))
    elif (jax.process_count() > 1
          and not isinstance(x, jax.core.Tracer)):
        # true cross-process semantics: the group's rank list (rank ==
        # process_index) becomes a submesh of one device per member process.
        # EVERY member (and only members) must call in — the same collective
        # contract as a NCCL subgroup (reference
        # test_collective_api_base.py); a non-member calling is a clear
        # error rather than a silent over-reduce or a hang.
        ranks = sorted(g.ranks)
        # device-granular classification FIRST: a group over device ids (not
        # process ranks) must get the shard_map guidance, not a misleading
        # membership error no process could ever satisfy
        if ranks and ranks[-1] >= jax.process_count():
            if ranks == list(range(jax.device_count())):
                ranks = sorted(range(jax.process_count()))  # device-world grp
            else:
                raise NotImplementedError(
                    f"eager multi-process all_reduce: group ranks {ranks} "
                    "exceed the process count — device-granular subgroups "
                    "run inside shard_map over the group's mesh axis")
        if jax.process_index() not in ranks:
            raise RuntimeError(
                f"process {jax.process_index()} is not a member of {g} — "
                "only (and all of) the group's member processes may call "
                "all_reduce(group=g)")
        out = _mp_all_reduce(x, op, ranks)
    else:
        n = g.nranks
        if op == ReduceOp.SUM:
            out = x * n
        elif op == ReduceOp.AVG or op in (ReduceOp.MAX, ReduceOp.MIN):
            out = x
        else:
            out = x**n
    if isinstance(tensor, Tensor):
        tensor._replace_(out, None, 0)
        return _task()
    return out


def all_gather(tensor_list: Optional[List], tensor: Tensor, group=None, sync_op=True, axis=0):
    g = _group(group)
    x = unwrap(tensor)
    if _axis_bound(g.axis_name):
        out = lax.all_gather(x, g.axis_name, axis=axis, tiled=False)
        parts = [out[i] for i in range(g.nranks)] if axis == 0 else list(jnp.moveaxis(out, axis, 0))
    else:
        parts = [x for _ in range(g.nranks)]
    if tensor_list is not None:
        tensor_list.clear()
        tensor_list.extend(wrap(p) for p in parts)
        return _task()
    return [wrap(p) for p in parts]


def all_gather_into_tensor(out_tensor, tensor, group=None, sync_op=True):
    g = _group(group)
    x = unwrap(tensor)
    if _axis_bound(g.axis_name):
        out = lax.all_gather(x, g.axis_name, axis=0, tiled=True)
    else:
        out = jnp.concatenate([x] * g.nranks, axis=0)
    if out_tensor is not None:
        out_tensor._replace_(out, None, 0)
        return _task()
    return wrap(out)


def all_gather_object(object_list, obj, group=None):
    g = _group(group)
    object_list.clear()
    object_list.extend(obj for _ in range(g.nranks))


def reduce_scatter(tensor: Tensor, tensor_or_tensor_list, op=ReduceOp.SUM, group=None, sync_op=True):
    g = _group(group)
    if isinstance(tensor_or_tensor_list, (list, tuple)):
        x = jnp.concatenate([unwrap(t) for t in tensor_or_tensor_list], axis=0)
    else:
        x = unwrap(tensor_or_tensor_list)
    if _axis_bound(g.axis_name):
        out = lax.psum_scatter(x, g.axis_name, scatter_dimension=0, tiled=True)
    else:
        n = g.nranks
        shard = x.shape[0] // n
        out = x[:shard] * (n if op == ReduceOp.SUM else 1)
    tensor._replace_(out, None, 0)
    return _task()


def broadcast(tensor: Tensor, src=0, group=None, sync_op=True):
    g = _group(group)
    x = unwrap(tensor)
    if _axis_bound(g.axis_name):
        # select src's value on every member: gather then index (XLA folds this)
        gathered = lax.all_gather(x, g.axis_name, axis=0, tiled=False)
        out = gathered[g.get_group_rank(src) if src in g.ranks else src]
    else:
        out = x
    tensor._replace_(out, None, 0)
    return _task()


def broadcast_object_list(object_list, src=0, group=None):
    return object_list


def reduce(tensor: Tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    # on TPU a reduce is an all_reduce whose non-dst results are unused (XLA DCEs them)
    return all_reduce(tensor, op, group, sync_op)


def scatter(tensor: Tensor, tensor_list=None, src=0, group=None, sync_op=True):
    g = _group(group)
    if _axis_bound(g.axis_name):
        stacked = jnp.stack([unwrap(t) for t in tensor_list], axis=0) if tensor_list else unwrap(tensor)
        idx = lax.axis_index(g.axis_name)
        out = lax.dynamic_index_in_dim(stacked, idx, axis=0, keepdims=False)
    else:
        out = unwrap(tensor_list[0]) if tensor_list else unwrap(tensor)
    tensor._replace_(out, None, 0)
    return _task()


def scatter_object_list(out_object_list, in_object_list=None, src=0, group=None):
    out_object_list.clear()
    out_object_list.append(in_object_list[0] if in_object_list else None)


def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    g = _group(group)
    if isinstance(in_tensor_list, (list, tuple)):
        x = jnp.stack([unwrap(t) for t in in_tensor_list], axis=0)
    else:
        x = unwrap(in_tensor_list)
    if _axis_bound(g.axis_name):
        out = lax.all_to_all(x, g.axis_name, split_axis=0, concat_axis=0, tiled=False)
    else:
        out = x
    parts = [out[i] for i in range(out.shape[0])]
    if out_tensor_list is not None:
        out_tensor_list.clear()
        out_tensor_list.extend(wrap(p) for p in parts)
        return _task()
    return [wrap(p) for p in parts]


all_to_all = alltoall


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None, out_split_sizes=None, group=None, sync_op=True):
    g = _group(group)
    x = unwrap(in_tensor)
    if _axis_bound(g.axis_name):
        out = lax.all_to_all(x, g.axis_name, split_axis=0, concat_axis=0, tiled=True)
    else:
        out = x
    if out_tensor is not None:
        out_tensor._replace_(out, None, 0)
        return _task()
    return wrap(out)


all_to_all_single = alltoall_single


def send(tensor, dst=0, group=None, sync_op=True):
    raise RuntimeError(
        "point-to-point send/recv only exist inside a pipeline shard_map on TPU "
        "(lax.ppermute edges) — use distributed.fleet PipelineParallel or p2p helpers"
    )


def recv(tensor, src=0, group=None, sync_op=True):
    raise RuntimeError(
        "point-to-point send/recv only exist inside a pipeline shard_map on TPU "
        "(lax.ppermute edges) — use distributed.fleet PipelineParallel or p2p helpers"
    )


def isend(tensor, dst=0, group=None):
    return send(tensor, dst, group)


def irecv(tensor, src=0, group=None):
    return recv(tensor, src, group)


class P2POp:
    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    raise RuntimeError("use pipeline ppermute edges (fleet.meta_parallel.p2p) on TPU")


def barrier(group=None):
    from ..resilience import faults as _faults

    # fault site: a delayed collective (docs/RESILIENCE.md) — the watchdog
    # and retry drills inject here to model a straggling/partitioned rank
    _faults.maybe_inject("collective", "barrier")
    jax.effects_barrier()
    return _task()


# in-shard_map helpers used by the manual fleet engines
def ppermute(x, axis_name, perm):
    return lax.ppermute(x, axis_name, perm)


def axis_index(axis_name):
    return lax.axis_index(axis_name)
