"""TCPStore — rank rendezvous key/value store.

Reference: paddle/phi/core/distributed/store/tcp_store.h:121 (MasterDaemon +
TCPStore client over sockets; used by ProcessGroup bootstrap at
python/paddle/distributed/parallel.py:1134 create_or_get_global_tcp_store).

TPU-native role: XLA owns the collective fabric, so the store is not needed to
exchange NCCL ids — it bootstraps the *job*: rendezvous for launch/elastic
(controllers), barriers for multi-host tests, and cross-process coordination
for the DataLoader and checkpoint writers. Backed by the C++ daemon in
paddle_tpu/native/src/tcp_store.cc; a pure-Python server/client fallback keeps
the API alive when no toolchain exists (PT_DISABLE_NATIVE=1).

Resilience (docs/RESILIENCE.md): every client op runs under a shared
retry/backoff policy — a transport failure (EOF, socket timeout, injected
fault) reconnects and retries instead of killing the job on the first EOF;
exhaustion raises RetryError with a PT-RETRY code. Protocol-level outcomes
(missing key, logical wait timeout) are decided *outside* the retried
region and are never retried. Fault-injection sites: ``store.client``
(before each client op) and ``store.daemon`` (pure-Python server, before
serving a command).
"""

from __future__ import annotations

import ctypes
import os
import pickle
import socket
import socketserver
import struct
import threading
import time
from typing import Optional

from ... import native
from ..resilience import faults as _faults
from ..resilience.retry import RetryPolicy, retry_call

__all__ = ["TCPStore", "MasterDaemon", "StoreRequestLost",
           "StoreAmbiguousError"]


class StoreRequestLost(ConnectionError):
    """Transport failed AFTER the request bytes were sent — the daemon may
    or may not have applied the op. Safe to retry only for idempotent ops."""


class StoreAmbiguousError(RuntimeError):
    """A non-idempotent op (add, compare_set) hit a post-send transport
    failure: retrying could double-apply (e.g. releasing a barrier early),
    so the ambiguity surfaces to the caller instead. Non-retryable."""

_CMD = {"set": 1, "get": 2, "add": 3, "check": 4, "delete": 5, "wait": 6,
        "num_keys": 7, "ping": 8, "wait_ge": 9, "compare_set": 10}
_CMD_NAME = {v: k for k, v in _CMD.items()}
_OK, _NOTFOUND, _TIMEOUT, _ERROR = 0, 1, 2, 3


def _resolve(host: str) -> str:
    try:
        return socket.gethostbyname(host)
    except OSError:
        return host


# ---------------------------------------------------------------------------
# Pure-Python fallback (same wire protocol as the native daemon)
# ---------------------------------------------------------------------------

class _PyState:
    def __init__(self):
        self.data = {}
        self.cond = threading.Condition()


class _Handler(socketserver.BaseRequestHandler):
    def _read(self, n):
        buf = b""
        while len(buf) < n:
            chunk = self.request.recv(n - len(buf))
            if not chunk:
                raise ConnectionError
            buf += chunk
        return buf

    def _read_blob(self):
        (n,) = struct.unpack("<I", self._read(4))
        return self._read(n) if n else b""

    def _resp(self, status, payload=b"", num=0):
        self.request.sendall(
            struct.pack("<BI", status, len(payload)) + payload + struct.pack("<q", num))

    def handle(self):
        st: _PyState = self.server.state  # type: ignore[attr-defined]
        try:
            while True:
                (cmd,) = struct.unpack("<B", self._read(1))
                key = self._read_blob().decode()
                val = self._read_blob()
                (arg,) = struct.unpack("<q", self._read(8))
                # fault site: a stalled/killed daemon op (outside the lock so
                # an injected stall never blocks other clients)
                _faults.maybe_inject(
                    "store.daemon", f"{_CMD_NAME.get(cmd, cmd)}:{key}")
                with st.cond:
                    if cmd == _CMD["set"]:
                        st.data[key] = val
                        st.cond.notify_all()
                        self._resp(_OK)
                    elif cmd == _CMD["get"]:
                        if key in st.data:
                            self._resp(_OK, st.data[key])
                        else:
                            self._resp(_NOTFOUND)
                    elif cmd == _CMD["add"]:
                        cur = _decode_i64(st.data.get(key, b"")) + arg
                        st.data[key] = struct.pack("<q", cur)
                        st.cond.notify_all()
                        self._resp(_OK, num=cur)
                    elif cmd == _CMD["check"]:
                        self._resp(_OK, num=int(key in st.data))
                    elif cmd == _CMD["delete"]:
                        self._resp(_OK, num=int(st.data.pop(key, None) is not None))
                    elif cmd == _CMD["wait"]:
                        ok = _cond_wait(st, arg, lambda: key in st.data)
                        self._resp(_OK if ok else _TIMEOUT)
                    elif cmd == _CMD["wait_ge"]:
                        timeout_ms = _decode_i64(val) if val else -1
                        ok = _cond_wait(
                            st, timeout_ms,
                            lambda: _decode_i64(st.data.get(key, b"")) >= arg)
                        self._resp(_OK if ok else _TIMEOUT,
                                   num=_decode_i64(st.data.get(key, b"")))
                    elif cmd == _CMD["num_keys"]:
                        self._resp(_OK, num=len(st.data))
                    elif cmd == _CMD["ping"]:
                        self._resp(_OK, num=arg)
                    elif cmd == _CMD["compare_set"]:
                        sep = val.find(b"\x00")
                        expected, desired = val[:sep], val[sep + 1:]
                        cur = st.data.get(key)
                        matched = (cur is None and expected == b"") or cur == expected
                        if matched:
                            st.data[key] = desired
                            st.cond.notify_all()
                        self._resp(_OK if matched else _ERROR,
                                   st.data.get(key, b""), int(matched))
                    else:
                        self._resp(_ERROR)
        except (ConnectionError, OSError):
            pass


def _decode_i64(v: bytes) -> int:
    if len(v) == 8:
        return struct.unpack("<q", v)[0]
    try:
        return int(v.decode())
    except Exception:
        return 0


def _cond_wait(st: _PyState, timeout_ms: int, pred) -> bool:
    deadline = None if timeout_ms < 0 else time.monotonic() + timeout_ms / 1000
    while not pred():
        remain = None if deadline is None else deadline - time.monotonic()
        if remain is not None and remain <= 0:
            return False
        st.cond.wait(remain if remain is None or remain < 0.2 else 0.2)
    return True


class MasterDaemon:
    """Store server. Native C++ daemon when available, threaded Python otherwise."""

    def __init__(self, port: int = 0):
        self._lib = native.load()
        if self._lib is not None:
            self._handle = self._lib.pt_store_master_start(port)
            if not self._handle:
                raise RuntimeError(f"TCPStore master failed to bind port {port}")
            self.port = self._lib.pt_store_master_port(self._handle)
            self._server = None
        else:
            self._handle = None
            srv = socketserver.ThreadingTCPServer(("0.0.0.0", port), _Handler,
                                                  bind_and_activate=False)
            srv.allow_reuse_address = True
            srv.daemon_threads = True
            srv.server_bind()
            srv.server_activate()
            srv.state = _PyState()  # type: ignore[attr-defined]
            self._server = srv
            self.port = srv.server_address[1]
            threading.Thread(target=srv.serve_forever, daemon=True).start()

    def stop(self):
        if self._handle is not None:
            self._lib.pt_store_master_stop(self._handle)
            self._handle = None
        elif self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class _PyClient:
    def __init__(self, host, port, timeout_ms):
        deadline = time.monotonic() + timeout_ms / 1000
        while True:
            try:
                self.sock = socket.create_connection((host, port), timeout=5)
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.sock.settimeout(None)
        self._lock = threading.Lock()

    def request(self, cmd, key=b"", val=b"", arg=0, timeout_s=None):
        """One wire round trip. ``timeout_s`` bounds the whole exchange so a
        hung daemon surfaces as a retryable socket timeout, not a dead job;
        after any transport error the connection state is undefined — the
        owner must reconnect."""
        with self._lock:
            self.sock.settimeout(timeout_s)
            msg = (struct.pack("<B", cmd) + struct.pack("<I", len(key)) + key +
                   struct.pack("<I", len(val)) + val + struct.pack("<q", arg))
            sent = False
            try:
                self.sock.sendall(msg)
                sent = True
                status = self._read(1)[0]
                (n,) = struct.unpack("<I", self._read(4))
                payload = self._read(n) if n else b""
                (num,) = struct.unpack("<q", self._read(8))
                return status, payload, num
            except (ConnectionError, OSError) as e:
                if sent and not isinstance(e, StoreRequestLost):
                    # the daemon may have applied the op before the link died
                    raise StoreRequestLost(str(e) or type(e).__name__) from e
                raise

    def _read(self, n):
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("store connection closed")
            buf += chunk
        return buf

    def close(self):
        self.sock.close()


class TCPStore:
    """Client (optionally hosting the master) — mirrors paddle's TCPStore API.

    >>> store = TCPStore("127.0.0.1", 0, is_master=True, world_size=1)
    >>> store.set("k", b"v"); store.get("k")
    b'v'

    Transport failures reconnect + retry under ``self._retry``. Idempotent
    ops (set/get/check/wait/...) retry unconditionally; non-idempotent ops
    (``add``, ``compare_set``) never retry a post-send failure — the
    outcome is unknown, so they raise StoreAmbiguousError instead of
    risking a double-apply (an over-counted barrier releases early).
    ``add(..., on_ambiguous="retry")`` opts back in for counters that
    tolerate over-count (heartbeats). Native-path caveat: pt_store_add /
    pt_store_wait_ge report io errors in-band as -1; a same-connection
    probe disambiguates a genuine -1 value, so negative counters are safe
    but cost one extra round trip when they hit exactly -1.
    """

    def __init__(self, host: str, port: int, is_master: bool = False,
                 world_size: int = 1, timeout: float = 300.0,
                 op_timeout: Optional[float] = None):
        self.host = _resolve(host)
        self.world_size = world_size
        self.timeout = timeout
        # bound on one non-waiting wire op (pure-Python client only — the
        # native client has no recv timeout, see docs/RESILIENCE.md; a hung
        # native daemon belongs to the CommTaskManager watchdog). Wait-style
        # ops add their logical timeout on top.
        self.op_timeout = (min(30.0, timeout) if op_timeout is None
                           else float(op_timeout))
        self._retry = RetryPolicy(
            max_attempts=int(os.environ.get("PT_STORE_RETRIES", "3")),
            base_delay=0.05, max_delay=1.0, deadline=timeout)
        self._daemon: Optional[MasterDaemon] = MasterDaemon(port) if is_master else None
        self.port = self._daemon.port if self._daemon else port
        self._lib = native.load()
        if self._lib is not None:
            self._client = self._lib.pt_store_client_new(
                self.host.encode(), self.port, int(timeout * 1000))
            if not self._client:
                raise RuntimeError(
                    f"TCPStore could not connect to {self.host}:{self.port}")
            self._py = None
        else:
            self._client = None
            self._py = _PyClient(self.host, self.port, int(timeout * 1000))

    # -- transport resilience ----------------------------------------------
    def _reconnect(self):
        if self._lib is not None:
            if self._client:
                try:
                    self._lib.pt_store_client_free(self._client)
                except Exception:
                    pass
            # short per-attempt connect window (vs the generous bootstrap
            # `timeout` in __init__): retry_call owns the overall deadline,
            # so each reconnect attempt must fail fast, not block for 300s
            self._client = self._lib.pt_store_client_new(
                self.host.encode(), self.port, int(self.op_timeout * 1000))
            if not self._client:
                raise ConnectionError(
                    f"store reconnect to {self.host}:{self.port} failed")
        else:
            if self._py is not None:
                try:
                    self._py.close()
                except OSError:
                    pass
            self._py = _PyClient(self.host, self.port,
                                 int(self.op_timeout * 1000))

    def _op(self, name: str, key: str, fn, ambiguous_ok: bool = True):
        """Run one client op under the retry policy. ``fn`` must raise
        ConnectionError/OSError/socket-timeout for transport failures only —
        protocol outcomes are returned and judged by the caller.

        ``ambiguous_ok=False`` (non-idempotent ops): a post-send transport
        failure (StoreRequestLost — the daemon may already have applied the
        op) is NOT retried; it surfaces as StoreAmbiguousError so e.g. a
        barrier arrival can never be double-counted into an early release.
        Pre-send failures are always safely retryable."""

        def attempt():
            _faults.maybe_inject("store.client", f"{name}:{key}")
            # a previous attempt's reconnect may have failed and left no
            # client at all — re-establish (raises ConnectionError while the
            # daemon is down, which retry_call treats like any transport
            # failure) so fn() never dispatches against a missing backend
            if (self._client is None if self._lib is not None
                    else self._py is None):
                self._reconnect()
            try:
                return fn()
            except (ConnectionError, OSError) as e:
                ambiguous = isinstance(e, StoreRequestLost)
                try:
                    self._reconnect()
                except Exception:
                    pass        # next attempt (or the caller) fails fast
                if ambiguous and not ambiguous_ok:
                    raise StoreAmbiguousError(
                        f"store {name}({key}): transport failed after send; "
                        "the op may or may not have been applied") from e
                raise

        return retry_call(attempt, policy=self._retry,
                          what=f"store.{name}({key})")

    # -- core ops ----------------------------------------------------------
    def set(self, key: str, value) -> None:
        v = value if isinstance(value, (bytes, bytearray)) else pickle.dumps(value)

        def do():
            if self._client:
                rc = self._lib.pt_store_set(self._client, key.encode(),
                                            bytes(v), len(v))
                if rc == -1:            # native io error: retryable
                    raise ConnectionError(f"store set({key}) io error")
                if rc != 0:
                    raise RuntimeError(f"store set({key}) failed rc={rc}")
                return None
            self._py.request(_CMD["set"], key.encode(), bytes(v),
                             timeout_s=self.op_timeout)

        self._op("set", key, do)

    def get(self, key: str, wait: bool = True) -> Optional[bytes]:
        if wait and not self.wait([key]):
            raise TimeoutError(f"store get({key}) timed out after {self.timeout}s")

        def do():
            if self._client:
                p = ctypes.POINTER(ctypes.c_uint8)()
                n = ctypes.c_int()
                st = self._lib.pt_store_get(self._client, key.encode(),
                                            ctypes.byref(p), ctypes.byref(n))
                data = native.take_bytes(self._lib, p, n)
                if st == -1:            # io error, NOT "key missing"
                    raise ConnectionError(f"store get({key}) io error")
                return data if st == _OK else None
            st, payload, _ = self._py.request(_CMD["get"], key.encode(),
                                              timeout_s=self.op_timeout)
            return payload if st == _OK else None

        return self._op("get", key, do)

    def add(self, key: str, amount: int = 1, *,
            on_ambiguous: str = "raise") -> int:
        """Atomic server-side increment. NOT idempotent: by default a
        post-send transport failure raises StoreAmbiguousError instead of
        retrying (a re-applied +1 could release a barrier early). Callers
        whose counters tolerate over-count (heartbeats, monotone progress
        markers) pass ``on_ambiguous="retry"``."""

        def do():
            if self._client:
                v = int(self._lib.pt_store_add(self._client, key.encode(),
                                               amount))
                if v == -1:
                    # -1 is in-band: io error OR a genuine counter value.
                    # Probe the same connection — a dead fd fails again, a
                    # healthy one proves -1 was the real value.
                    if int(self._lib.pt_store_num_keys(self._client)) == -1:
                        raise StoreRequestLost(f"store add({key}) io error")
                    return v
                return v
            _, _, num = self._py.request(_CMD["add"], key.encode(), arg=amount,
                                         timeout_s=self.op_timeout)
            return num

        return self._op("add", key, do,
                        ambiguous_ok=(on_ambiguous == "retry"))

    def check(self, keys) -> bool:
        keys = [keys] if isinstance(keys, str) else keys
        for k in keys:
            def do(k=k):
                if self._client:
                    rc = self._lib.pt_store_check(self._client, k.encode())
                    if rc == -1:
                        raise ConnectionError(f"store check({k}) io error")
                    return rc == 1
                _, _, num = self._py.request(_CMD["check"], k.encode(),
                                             timeout_s=self.op_timeout)
                return bool(num)

            if not self._op("check", k, do):
                return False
        return True

    def delete_key(self, key: str) -> bool:
        def do():
            if self._client:
                rc = self._lib.pt_store_delete(self._client, key.encode())
                if rc == -1:
                    raise ConnectionError(f"store delete({key}) io error")
                return rc == 1
            _, _, num = self._py.request(_CMD["delete"], key.encode(),
                                         timeout_s=self.op_timeout)
            return bool(num)

        return self._op("delete", key, do)

    def wait(self, keys, timeout: Optional[float] = None) -> bool:
        keys = [keys] if isinstance(keys, str) else keys
        tmo = int((self.timeout if timeout is None else timeout) * 1000)
        sock_tmo = None if tmo < 0 else tmo / 1000 + self.op_timeout
        for k in keys:
            def do(k=k):
                if self._client:
                    st = self._lib.pt_store_wait(self._client, k.encode(),
                                                 tmo)
                    if st == -1:        # io error != logical timeout
                        raise ConnectionError(f"store wait({k}) io error")
                    return st
                st, _, _ = self._py.request(_CMD["wait"], k.encode(), arg=tmo,
                                            timeout_s=sock_tmo)
                return st

            if self._op("wait", k, do) != _OK:
                return False            # logical timeout: an answer, no retry
        return True

    def wait_ge(self, key: str, target: int, timeout: Optional[float] = None) -> int:
        """Block until int(store[key]) >= target; returns the value seen."""
        tmo = int((self.timeout if timeout is None else timeout) * 1000)
        sock_tmo = None if tmo < 0 else tmo / 1000 + self.op_timeout

        def do():
            if self._client:
                v = int(self._lib.pt_store_wait_ge(self._client,
                                                   key.encode(), target, tmo))
                if v == -1:             # in-band: io error or real value -1
                    if int(self._lib.pt_store_num_keys(self._client)) == -1:
                        raise ConnectionError(
                            f"store wait_ge({key}) io error")
                return v
            st, _, num = self._py.request(_CMD["wait_ge"], key.encode(),
                                          struct.pack("<q", tmo), target,
                                          timeout_s=sock_tmo)
            return -2 if st == _TIMEOUT else num

        v = self._op("wait_ge", key, do)
        if v == -2:
            raise TimeoutError(f"wait_ge({key}, {target}) timed out")
        return v

    def compare_set(self, key: str, expected: bytes, desired: bytes) -> bool:
        def do():
            if self._client:
                p = ctypes.POINTER(ctypes.c_uint8)()
                n = ctypes.c_int()
                rc = self._lib.pt_store_compare_set(
                    self._client, key.encode(), expected, len(expected),
                    desired, len(desired), ctypes.byref(p), ctypes.byref(n))
                native.take_bytes(self._lib, p, n)
                if rc == -1:
                    raise StoreRequestLost(
                        f"store compare_set({key}) io error")
                return rc == 1
            st, _, num = self._py.request(_CMD["compare_set"], key.encode(),
                                          expected + b"\x00" + desired,
                                          timeout_s=self.op_timeout)
            return bool(num)

        return self._op("compare_set", key, do, ambiguous_ok=False)

    def num_keys(self) -> int:
        def do():
            if self._client:
                v = int(self._lib.pt_store_num_keys(self._client))
                if v == -1:
                    raise ConnectionError("store num_keys io error")
                return v
            _, _, num = self._py.request(_CMD["num_keys"],
                                         timeout_s=self.op_timeout)
            return num

        return self._op("num_keys", "", do)

    # -- composite ---------------------------------------------------------
    def barrier(self, name: str = "default", world_size: Optional[int] = None,
                timeout: Optional[float] = None) -> None:
        """All `world_size` callers block until everyone arrives."""
        ws = world_size or self.world_size
        self.add(f"__barrier__/{name}", 1)
        self.wait_ge(f"__barrier__/{name}", ws, timeout)

    def close(self):
        if self._client:
            self._lib.pt_store_client_free(self._client)
            self._client = None
        if self._py:
            self._py.close()
            self._py = None
        if self._daemon:
            self._daemon.stop()
            self._daemon = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
