"""TCPStore — rank rendezvous key/value store.

Reference: paddle/phi/core/distributed/store/tcp_store.h:121 (MasterDaemon +
TCPStore client over sockets; used by ProcessGroup bootstrap at
python/paddle/distributed/parallel.py:1134 create_or_get_global_tcp_store).

TPU-native role: XLA owns the collective fabric, so the store is not needed to
exchange NCCL ids — it bootstraps the *job*: rendezvous for launch/elastic
(controllers), barriers for multi-host tests, and cross-process coordination
for the DataLoader and checkpoint writers. Backed by the C++ daemon in
paddle_tpu/native/src/tcp_store.cc; a pure-Python server/client fallback keeps
the API alive when no toolchain exists (PT_DISABLE_NATIVE=1).
"""

from __future__ import annotations

import ctypes
import pickle
import socket
import socketserver
import struct
import threading
import time
from typing import Optional

from ... import native

__all__ = ["TCPStore", "MasterDaemon"]

_CMD = {"set": 1, "get": 2, "add": 3, "check": 4, "delete": 5, "wait": 6,
        "num_keys": 7, "ping": 8, "wait_ge": 9, "compare_set": 10}
_OK, _NOTFOUND, _TIMEOUT, _ERROR = 0, 1, 2, 3


def _resolve(host: str) -> str:
    try:
        return socket.gethostbyname(host)
    except OSError:
        return host


# ---------------------------------------------------------------------------
# Pure-Python fallback (same wire protocol as the native daemon)
# ---------------------------------------------------------------------------

class _PyState:
    def __init__(self):
        self.data = {}
        self.cond = threading.Condition()


class _Handler(socketserver.BaseRequestHandler):
    def _read(self, n):
        buf = b""
        while len(buf) < n:
            chunk = self.request.recv(n - len(buf))
            if not chunk:
                raise ConnectionError
            buf += chunk
        return buf

    def _read_blob(self):
        (n,) = struct.unpack("<I", self._read(4))
        return self._read(n) if n else b""

    def _resp(self, status, payload=b"", num=0):
        self.request.sendall(
            struct.pack("<BI", status, len(payload)) + payload + struct.pack("<q", num))

    def handle(self):
        st: _PyState = self.server.state  # type: ignore[attr-defined]
        try:
            while True:
                (cmd,) = struct.unpack("<B", self._read(1))
                key = self._read_blob().decode()
                val = self._read_blob()
                (arg,) = struct.unpack("<q", self._read(8))
                with st.cond:
                    if cmd == _CMD["set"]:
                        st.data[key] = val
                        st.cond.notify_all()
                        self._resp(_OK)
                    elif cmd == _CMD["get"]:
                        if key in st.data:
                            self._resp(_OK, st.data[key])
                        else:
                            self._resp(_NOTFOUND)
                    elif cmd == _CMD["add"]:
                        cur = _decode_i64(st.data.get(key, b"")) + arg
                        st.data[key] = struct.pack("<q", cur)
                        st.cond.notify_all()
                        self._resp(_OK, num=cur)
                    elif cmd == _CMD["check"]:
                        self._resp(_OK, num=int(key in st.data))
                    elif cmd == _CMD["delete"]:
                        self._resp(_OK, num=int(st.data.pop(key, None) is not None))
                    elif cmd == _CMD["wait"]:
                        ok = _cond_wait(st, arg, lambda: key in st.data)
                        self._resp(_OK if ok else _TIMEOUT)
                    elif cmd == _CMD["wait_ge"]:
                        timeout_ms = _decode_i64(val) if val else -1
                        ok = _cond_wait(
                            st, timeout_ms,
                            lambda: _decode_i64(st.data.get(key, b"")) >= arg)
                        self._resp(_OK if ok else _TIMEOUT,
                                   num=_decode_i64(st.data.get(key, b"")))
                    elif cmd == _CMD["num_keys"]:
                        self._resp(_OK, num=len(st.data))
                    elif cmd == _CMD["ping"]:
                        self._resp(_OK, num=arg)
                    elif cmd == _CMD["compare_set"]:
                        sep = val.find(b"\x00")
                        expected, desired = val[:sep], val[sep + 1:]
                        cur = st.data.get(key)
                        matched = (cur is None and expected == b"") or cur == expected
                        if matched:
                            st.data[key] = desired
                            st.cond.notify_all()
                        self._resp(_OK if matched else _ERROR,
                                   st.data.get(key, b""), int(matched))
                    else:
                        self._resp(_ERROR)
        except (ConnectionError, OSError):
            pass


def _decode_i64(v: bytes) -> int:
    if len(v) == 8:
        return struct.unpack("<q", v)[0]
    try:
        return int(v.decode())
    except Exception:
        return 0


def _cond_wait(st: _PyState, timeout_ms: int, pred) -> bool:
    deadline = None if timeout_ms < 0 else time.monotonic() + timeout_ms / 1000
    while not pred():
        remain = None if deadline is None else deadline - time.monotonic()
        if remain is not None and remain <= 0:
            return False
        st.cond.wait(remain if remain is None or remain < 0.2 else 0.2)
    return True


class MasterDaemon:
    """Store server. Native C++ daemon when available, threaded Python otherwise."""

    def __init__(self, port: int = 0):
        self._lib = native.load()
        if self._lib is not None:
            self._handle = self._lib.pt_store_master_start(port)
            if not self._handle:
                raise RuntimeError(f"TCPStore master failed to bind port {port}")
            self.port = self._lib.pt_store_master_port(self._handle)
            self._server = None
        else:
            self._handle = None
            srv = socketserver.ThreadingTCPServer(("0.0.0.0", port), _Handler,
                                                  bind_and_activate=False)
            srv.allow_reuse_address = True
            srv.daemon_threads = True
            srv.server_bind()
            srv.server_activate()
            srv.state = _PyState()  # type: ignore[attr-defined]
            self._server = srv
            self.port = srv.server_address[1]
            threading.Thread(target=srv.serve_forever, daemon=True).start()

    def stop(self):
        if self._handle is not None:
            self._lib.pt_store_master_stop(self._handle)
            self._handle = None
        elif self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class _PyClient:
    def __init__(self, host, port, timeout_ms):
        deadline = time.monotonic() + timeout_ms / 1000
        while True:
            try:
                self.sock = socket.create_connection((host, port), timeout=5)
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.sock.settimeout(None)
        self._lock = threading.Lock()

    def request(self, cmd, key=b"", val=b"", arg=0):
        with self._lock:
            msg = (struct.pack("<B", cmd) + struct.pack("<I", len(key)) + key +
                   struct.pack("<I", len(val)) + val + struct.pack("<q", arg))
            self.sock.sendall(msg)
            status = self._read(1)[0]
            (n,) = struct.unpack("<I", self._read(4))
            payload = self._read(n) if n else b""
            (num,) = struct.unpack("<q", self._read(8))
            return status, payload, num

    def _read(self, n):
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("store connection closed")
            buf += chunk
        return buf

    def close(self):
        self.sock.close()


class TCPStore:
    """Client (optionally hosting the master) — mirrors paddle's TCPStore API.

    >>> store = TCPStore("127.0.0.1", 0, is_master=True, world_size=1)
    >>> store.set("k", b"v"); store.get("k")
    b'v'
    """

    def __init__(self, host: str, port: int, is_master: bool = False,
                 world_size: int = 1, timeout: float = 300.0):
        self.host = _resolve(host)
        self.world_size = world_size
        self.timeout = timeout
        self._daemon: Optional[MasterDaemon] = MasterDaemon(port) if is_master else None
        self.port = self._daemon.port if self._daemon else port
        self._lib = native.load()
        if self._lib is not None:
            self._client = self._lib.pt_store_client_new(
                self.host.encode(), self.port, int(timeout * 1000))
            if not self._client:
                raise RuntimeError(
                    f"TCPStore could not connect to {self.host}:{self.port}")
            self._py = None
        else:
            self._client = None
            self._py = _PyClient(self.host, self.port, int(timeout * 1000))

    # -- core ops ----------------------------------------------------------
    def set(self, key: str, value) -> None:
        v = value if isinstance(value, (bytes, bytearray)) else pickle.dumps(value)
        if self._client:
            rc = self._lib.pt_store_set(self._client, key.encode(), bytes(v), len(v))
            if rc != 0:
                raise RuntimeError(f"store set({key}) failed rc={rc}")
        else:
            self._py.request(_CMD["set"], key.encode(), bytes(v))

    def get(self, key: str, wait: bool = True) -> Optional[bytes]:
        if wait and not self.wait([key]):
            raise TimeoutError(f"store get({key}) timed out after {self.timeout}s")
        if self._client:
            p = ctypes.POINTER(ctypes.c_uint8)()
            n = ctypes.c_int()
            st = self._lib.pt_store_get(self._client, key.encode(),
                                        ctypes.byref(p), ctypes.byref(n))
            data = native.take_bytes(self._lib, p, n)
            return data if st == _OK else None
        st, payload, _ = self._py.request(_CMD["get"], key.encode())
        return payload if st == _OK else None

    def add(self, key: str, amount: int = 1) -> int:
        if self._client:
            return int(self._lib.pt_store_add(self._client, key.encode(), amount))
        _, _, num = self._py.request(_CMD["add"], key.encode(), arg=amount)
        return num

    def check(self, keys) -> bool:
        keys = [keys] if isinstance(keys, str) else keys
        for k in keys:
            if self._client:
                if self._lib.pt_store_check(self._client, k.encode()) != 1:
                    return False
            else:
                _, _, num = self._py.request(_CMD["check"], k.encode())
                if not num:
                    return False
        return True

    def delete_key(self, key: str) -> bool:
        if self._client:
            return self._lib.pt_store_delete(self._client, key.encode()) == 1
        _, _, num = self._py.request(_CMD["delete"], key.encode())
        return bool(num)

    def wait(self, keys, timeout: Optional[float] = None) -> bool:
        keys = [keys] if isinstance(keys, str) else keys
        tmo = int((self.timeout if timeout is None else timeout) * 1000)
        for k in keys:
            if self._client:
                if self._lib.pt_store_wait(self._client, k.encode(), tmo) != _OK:
                    return False
            else:
                st, _, _ = self._py.request(_CMD["wait"], k.encode(), arg=tmo)
                if st != _OK:
                    return False
        return True

    def wait_ge(self, key: str, target: int, timeout: Optional[float] = None) -> int:
        """Block until int(store[key]) >= target; returns the value seen."""
        tmo = int((self.timeout if timeout is None else timeout) * 1000)
        if self._client:
            v = int(self._lib.pt_store_wait_ge(self._client, key.encode(), target, tmo))
            if v == -2:
                raise TimeoutError(f"wait_ge({key}, {target}) timed out")
            if v < 0:
                raise RuntimeError(f"wait_ge({key}) io error")
            return v
        st, _, num = self._py.request(_CMD["wait_ge"], key.encode(),
                                      struct.pack("<q", tmo), target)
        if st == _TIMEOUT:
            raise TimeoutError(f"wait_ge({key}, {target}) timed out")
        return num

    def compare_set(self, key: str, expected: bytes, desired: bytes) -> bool:
        if self._client:
            p = ctypes.POINTER(ctypes.c_uint8)()
            n = ctypes.c_int()
            rc = self._lib.pt_store_compare_set(
                self._client, key.encode(), expected, len(expected),
                desired, len(desired), ctypes.byref(p), ctypes.byref(n))
            native.take_bytes(self._lib, p, n)
            return rc == 1
        st, _, num = self._py.request(_CMD["compare_set"], key.encode(),
                                      expected + b"\x00" + desired)
        return bool(num)

    def num_keys(self) -> int:
        if self._client:
            return int(self._lib.pt_store_num_keys(self._client))
        _, _, num = self._py.request(_CMD["num_keys"])
        return num

    # -- composite ---------------------------------------------------------
    def barrier(self, name: str = "default", world_size: Optional[int] = None,
                timeout: Optional[float] = None) -> None:
        """All `world_size` callers block until everyone arrives."""
        ws = world_size or self.world_size
        self.add(f"__barrier__/{name}", 1)
        self.wait_ge(f"__barrier__/{name}", ws, timeout)

    def close(self):
        if self._client:
            self._lib.pt_store_client_free(self._client)
            self._client = None
        if self._py:
            self._py.close()
            self._py = None
        if self._daemon:
            self._daemon.stop()
            self._daemon = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
