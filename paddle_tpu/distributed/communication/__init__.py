from .functional import *  # noqa: F401,F403
from .group import Group, ReduceOp, get_group, is_initialized, new_group  # noqa: F401
