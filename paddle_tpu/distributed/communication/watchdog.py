"""Comm watchdog — hang/timeout detection for blocking distributed regions.

Reference: paddle/phi/core/distributed/comm_task_manager.h:37 (CommTaskManager
background thread + CommTask::IsTimeout at comm_task.h:127, stack dump on
timeout). TPU-native: there are no NCCL streams to poll; the watchdog brackets
blocking host regions (collective fences, store barriers, pipeline steps,
checkpoint IO). Backed by the C++ monitor thread in
paddle_tpu/native/src/watchdog.cc with a Python-thread fallback.

Usage::

    mgr = CommTaskManager(report_path="hang.jsonl")
    with mgr.task("allreduce/grads", timeout=120.0):
        jax.device_get(loss)   # fenced region
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Optional

from ... import native

__all__ = ["CommTaskManager", "get_comm_task_manager"]


class _PyWatchdog:
    def __init__(self, interval_ms: int, report_path: str):
        self.interval = interval_ms / 1000
        self.report_path = report_path
        self.tasks = {}
        self.next_id = 1
        self.timeouts = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def begin(self, name, timeout_ms):
        with self._lock:
            tid = self.next_id
            self.next_id += 1
            deadline = None if timeout_ms < 0 else time.monotonic() + timeout_ms / 1000
            self.tasks[tid] = [name, time.monotonic(), deadline, False]
            return tid

    def end(self, tid):
        with self._lock:
            self.tasks.pop(tid, None)

    def _loop(self):
        while not self._stop.wait(self.interval):
            now = time.monotonic()
            with self._lock:
                for rec in self.tasks.values():
                    name, start, deadline, reported = rec
                    if reported or deadline is None or now < deadline:
                        continue
                    rec[3] = True
                    self.timeouts += 1
                    try:
                        with open(self.report_path, "a") as f:
                            f.write(json.dumps({
                                "event": "watchdog_timeout", "task": name,
                                "pid": os.getpid(),
                                "elapsed_ms": int((now - start) * 1000),
                                "active_tasks": len(self.tasks)}) + "\n")
                    except OSError:
                        pass
                    if os.environ.get("PT_WATCHDOG_FATAL") == "1":
                        os._exit(99)

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=2)


class CommTaskManager:
    """Tracks blocking tasks; a monitor thread reports any that exceed their deadline."""

    def __init__(self, interval_ms: int = 1000,
                 report_path: Optional[str] = None,
                 default_timeout: float = 1800.0):
        self.report_path = report_path or os.environ.get(
            "PT_WATCHDOG_REPORT", "paddle_tpu_watchdog.jsonl")
        self.default_timeout = default_timeout
        self._lib = native.load()
        if self._lib is not None:
            self._handle = self._lib.pt_watchdog_start(
                interval_ms, self.report_path.encode())
            self._py = None
        else:
            self._handle = None
            self._py = _PyWatchdog(interval_ms, self.report_path)

    def begin(self, name: str, timeout: Optional[float] = None) -> int:
        tmo_ms = int((self.default_timeout if timeout is None else timeout) * 1000)
        if self._handle is not None:
            return int(self._lib.pt_watchdog_begin(self._handle, name.encode(), tmo_ms))
        return self._py.begin(name, tmo_ms)

    def end(self, task_id: int) -> None:
        if self._handle is not None:
            self._lib.pt_watchdog_end(self._handle, task_id)
        else:
            self._py.end(task_id)

    @contextlib.contextmanager
    def task(self, name: str, timeout: Optional[float] = None):
        tid = self.begin(name, timeout)
        try:
            yield
        finally:
            self.end(tid)

    @property
    def timeout_count(self) -> int:
        if self._handle is not None:
            return int(self._lib.pt_watchdog_timeout_count(self._handle))
        return self._py.timeouts

    @property
    def active_count(self) -> int:
        if self._handle is not None:
            return int(self._lib.pt_watchdog_active_count(self._handle))
        return len(self._py.tasks)

    def shutdown(self):
        if self._handle is not None:
            self._lib.pt_watchdog_stop(self._handle)
            self._handle = None
        elif self._py is not None:
            self._py.stop()
            self._py = None

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass


_global_mgr: Optional[CommTaskManager] = None
_global_lock = threading.Lock()


def get_comm_task_manager() -> CommTaskManager:
    global _global_mgr
    with _global_lock:
        if _global_mgr is None:
            _global_mgr = CommTaskManager()
        return _global_mgr
