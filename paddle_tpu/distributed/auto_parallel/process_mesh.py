"""ProcessMesh (reference: phi/core/distributed/auto_parallel/process_mesh.h +
python/paddle/distributed/auto_parallel/process_mesh.py).

TPU-native: a ProcessMesh IS a ``jax.sharding.Mesh`` — an N-D array of devices with
named axes. DistTensor placements map to ``PartitionSpec`` entries over those axes.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import numpy as np


class ProcessMesh:
    def __init__(self, mesh=None, dim_names: Optional[Sequence[str]] = None, shape=None, process_ids=None):
        if mesh is not None:
            arr = np.asarray(mesh)
        else:
            arr = np.arange(int(np.prod(shape))).reshape(shape)
        self._ids = arr
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        self._dim_names = list(dim_names)
        self._jax_mesh = None

    @property
    def shape(self) -> List[int]:
        return list(self._ids.shape)

    @property
    def ndim(self) -> int:
        return self._ids.ndim

    @property
    def dim_names(self) -> List[str]:
        return list(self._dim_names)

    @property
    def process_ids(self) -> List[int]:
        return self._ids.reshape(-1).tolist()

    @property
    def mesh(self):
        return self._ids

    def get_dim_size(self, name) -> int:
        return self._ids.shape[self._dim_names.index(name)]

    def get_mesh_with_dim(self, dim_name, index=None):
        axis = self._dim_names.index(dim_name)
        moved = np.moveaxis(self._ids, axis, 0)
        names = [dim_name] + [n for n in self._dim_names if n != dim_name]
        if index is not None:
            return ProcessMesh(moved[index], names[1:])
        return ProcessMesh(moved, names)

    def to_jax(self) -> jax.sharding.Mesh:
        """Materialize as a jax Mesh over real devices (cached)."""
        if self._jax_mesh is None:
            devices = jax.devices()
            dev_map = {d.id: d for d in devices}
            flat = self._ids.reshape(-1)
            try:
                dev_arr = np.array([dev_map[int(i)] for i in flat], dtype=object).reshape(self._ids.shape)
            except KeyError:
                # process ids beyond local devices (multi-host logical mesh): index order
                dev_arr = np.array(devices[: flat.size], dtype=object).reshape(self._ids.shape)
            self._jax_mesh = jax.sharding.Mesh(dev_arr, tuple(self._dim_names))
        return self._jax_mesh

    def __eq__(self, other):
        return isinstance(other, ProcessMesh) and np.array_equal(self._ids, other._ids) and self._dim_names == other._dim_names

    def __hash__(self):
        return hash((self._ids.tobytes(), tuple(self._dim_names)))

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self._dim_names})"

    def __enter__(self):
        _mesh_stack.append(self)
        return self

    def __exit__(self, *exc):
        _mesh_stack.pop()
        return False


_mesh_stack: List[ProcessMesh] = []


def get_current_mesh() -> Optional[ProcessMesh]:
    return _mesh_stack[-1] if _mesh_stack else None


def auto_mesh(dim_names: Sequence[str], shape: Sequence[int]) -> ProcessMesh:
    """Build a mesh over all visible devices with the given logical shape."""
    n = int(np.prod(shape))
    assert n == jax.device_count(), f"mesh size {n} != device_count {jax.device_count()}"
    return ProcessMesh(np.arange(n).reshape(shape), dim_names)
