"""paddle_tpu.distributed.auto_parallel (reference: python/paddle/distributed/auto_parallel)."""

from .api import (  # noqa: F401
    Strategy,
    dtensor_from_local,
    dtensor_to_local,
    get_mesh,
    reshard,
    set_mesh,
    shard_dataloader,
    shard_layer,
    shard_optimizer,
    shard_tensor,
    to_static,
    unshard_dtensor,
)
from .comm_programs import moe_combine_comm, train_step_comm  # noqa: F401
from .engine import Engine, ShardedTrainer  # noqa: F401
from .logical_sharding import (  # noqa: F401
    DEFAULT_RULES,
    annotate,
    axis_rules,
    constrain,
    current_mesh,
    logical_to_spec,
    make_mesh,
    param_sharding,
    shard_params,
)
from .placement import Partial, Placement, Replicate, Shard  # noqa: F401
from .process_mesh import ProcessMesh, auto_mesh, get_current_mesh  # noqa: F401
