"""SPMD pipeline parallelism over a ``pp`` mesh axis.

Parity anchor: the reference's dygraph pipeline engine
(/root/reference/python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py:231,
forward_backward_pipeline 1F1B at :547, interleaved VPP at :1143) and its P2P layer
(pp_utils/p2p_communication.py:51 SendRecvMeta shape negotiation).

TPU-native redesign: no per-rank Python schedule, no NCCL P2P, no shape
negotiation. The whole pipeline is ONE jitted SPMD program:

  - layer weights are STACKED along a leading axis sharded over the ``pp`` mesh
    axis — each device materialises only its stage's layers;
  - ``jax.shard_map`` with ``axis_names={"pp"}`` makes only the pp axis manual;
    every other mesh axis (dp/fsdp/tp/sep) stays in GSPMD "auto" mode, so the
    in-stage compute is still sharded by the usual logical-axis rules;
  - activations move between stages with ``lax.ppermute`` (compiles to
    collective-permute riding ICI);
  - the schedule is a ``lax.scan`` over ``n_micro + n_stages - 1`` ticks — the
    GPipe fill/drain pattern. Backward needs no hand-written 1F1B state machine:
    the transpose of ppermute is the reverse rotation, so ``jax.grad`` through
    the scan IS the reverse pipeline schedule. XLA's scheduler overlaps the
    collective-permute with compute (the job NCCL streams did in the reference).

Memory note: GPipe-style stashing of all microbatch activations is avoided by
``remat=True`` (per-block rematerialisation), which is how 1F1B's memory benefit
is obtained in the XLA world.
"""

from __future__ import annotations

import threading
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_state = threading.local()


def in_manual_pipeline() -> bool:
    """True while tracing inside the shard_map(pp) body.

    Layer code that opens its own shard_map (flash attention, ring attention)
    must take the plain auto-sharded path instead — nested manual meshes over
    the same axes are not composable.
    """
    return getattr(_state, "manual", False)


class _ManualCtx:
    def __enter__(self):
        self._prev = in_manual_pipeline()
        _state.manual = True

    def __exit__(self, *exc):
        _state.manual = self._prev
        return False


def gpipe_schedule(stage_fn: Callable, n_stages: int, axis_name: str = "pp",
                   with_aux: bool = False):
    """The GPipe tick schedule, to run INSIDE shard_map where ``axis_name`` is
    manual. ``stage_fn(stage_params, x, *bargs) -> y`` computes one stage
    (``-> (y, aux)`` when ``with_aux``; aux is a scalar summed over active
    ticks and psum'd over stages — MoE load-balance losses ride this).
    Returns ``pipeline(params, micro_inputs, *bargs) -> micro_outputs`` (or
    ``(micro_outputs, aux_total)``) where ``micro_inputs`` is ``[n_micro, ...]``
    (replicated over the pp axis) and the result is psum-replicated from the
    last stage.
    """
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def pipeline(params, micro_in, *bargs):
        n_micro = micro_in.shape[0]
        stage = jax.lax.axis_index(axis_name)
        total_ticks = n_micro + n_stages - 1

        def tick(carry, t):
            buf, outs, aux_acc = carry
            mb_idx = jnp.clip(t, 0, n_micro - 1)
            inject = jax.lax.dynamic_index_in_dim(micro_in, mb_idx, 0, keepdims=False)
            h = jnp.where(stage == 0, inject, buf)
            with _ManualCtx():
                res = stage_fn(params, h, *bargs)
            y, aux = res if with_aux else (res, None)
            if with_aux:
                # bubble ticks run on garbage activations — mask their aux
                active = (t - stage >= 0) & (t - stage < n_micro)
                aux_acc = aux_acc + jnp.where(active, aux, 0.0)
            out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            is_out = (stage == n_stages - 1) & (t >= n_stages - 1)
            prev = jax.lax.dynamic_index_in_dim(outs, out_idx, 0, keepdims=False)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(is_out, y, prev), out_idx, 0)
            nxt = jax.lax.ppermute(y, axis_name, perm)
            return (nxt, outs, aux_acc), None

        buf0 = jnp.zeros(micro_in.shape[1:], micro_in.dtype)
        outs0 = jnp.zeros(micro_in.shape, micro_in.dtype)
        aux0 = jnp.zeros((), jnp.float32)
        (_, outs, aux_acc), _ = jax.lax.scan(
            tick, (buf0, outs0, aux0), jnp.arange(total_ticks))
        # results live on the last stage; zero elsewhere + psum replicates them
        outs = jnp.where(stage == n_stages - 1, outs, jnp.zeros_like(outs))
        outs = jax.lax.psum(outs, axis_name)
        if with_aux:
            return outs, jax.lax.psum(aux_acc, axis_name)
        return outs

    return pipeline


def pipeline_call(
    block_fn: Callable,
    stacked_params: Sequence[jax.Array],
    x: jax.Array,
    *broadcast_args,
    mesh: Mesh,
    n_micro: int,
    axis_name: str = "pp",
    remat: bool = False,
    with_aux: bool = False,
):
    """Run ``x`` through ``n_layers`` stacked blocks, pipelined over ``axis_name``.

    Args:
      block_fn: ``block_fn(per_layer_params, x, *broadcast_args) -> y`` runs ONE
        block (``-> (y, aux_scalar)`` when ``with_aux`` — e.g. MoE gate losses);
        ``per_layer_params`` is a list of arrays without the stacking dim.
      stacked_params: arrays of shape ``[n_layers, ...]``; the leading dim must be
        divisible by the pp axis size (layers are assigned contiguously).
      x: global activations ``[batch, ...]``; batch must divide ``n_micro``.
      broadcast_args: extra per-call inputs replicated to every stage (e.g. rope
        tables).
      n_micro: number of microbatches (the reference's ``accumulate_steps``).
      remat: rematerialise each block in backward (fleet/recompute parity).

    Returns global activations with the same shape as ``x`` (plus the aux sum
    over all layers and microbatches when ``with_aux``).
    """
    n_stages = mesh.shape[axis_name]
    blk = jax.checkpoint(block_fn) if remat else block_fn

    def stage_fn(local_params, h, *bargs):
        # local_params: [layers_per_stage, ...] slices of this stage
        def body(carry, i):
            h, aux = carry
            wl = [w[i] for w in local_params]
            res = blk(wl, h, *bargs)
            if with_aux:
                y, a = res
                return (y, aux + a), None
            return (res, aux), None

        (h, aux), _ = jax.lax.scan(
            body, (h, jnp.zeros((), jnp.float32)),
            jnp.arange(local_params[0].shape[0]))
        return (h, aux) if with_aux else h

    if n_stages == 1:
        return stage_fn(list(stacked_params), x, *broadcast_args)

    batch = x.shape[0]
    if batch % n_micro != 0:
        raise ValueError(f"batch {batch} not divisible by n_micro {n_micro}")
    mb = batch // n_micro
    micro = x.reshape((n_micro, mb) + x.shape[1:])

    pipeline = gpipe_schedule(stage_fn, n_stages, axis_name, with_aux=with_aux)
    n_params = len(stacked_params)
    out_specs = (P(), P()) if with_aux else P()
    smapped = jax.shard_map(
        pipeline,
        mesh=mesh,
        in_specs=(tuple(P(axis_name) for _ in range(n_params)), P())
        + tuple(P() for _ in broadcast_args),
        out_specs=out_specs,
        axis_names=frozenset({axis_name}),
        check_vma=False,
    )
    res = smapped(tuple(stacked_params), micro, *broadcast_args)
    if with_aux:
        out, aux = res
        return out.reshape(x.shape), aux
    return res.reshape(x.shape)


def stack_block_params(blocks, mesh=None, axis_name: str = "pp"):
    """Stack per-block parameter Tensors into ``[n_layers, ...]`` arrays.

    Returns (stacked_arrays, shardings, names, decay_mask). All blocks must have
    identical parameter structure (true for transformer decoder stacks). The
    leading dim is sharded over ``axis_name``; trailing dims follow each param's
    logical axes — so pp composes with fsdp/tp sharding of the weights
    (the reference's PP×sharding×MP hybrid, fleet/base/topology.py:70).
    """
    from jax.sharding import NamedSharding
    from .logical_sharding import logical_to_spec

    per_block = [[t for _, t in b.named_parameters()] for b in blocks]
    names = [n for n, _ in blocks[0].named_parameters()]
    n_params = len(per_block[0])
    for pb in per_block:
        if len(pb) != n_params:
            raise ValueError("pipeline blocks have differing parameter structure")
    frozen = [n for n, t in blocks[0].named_parameters() if t.stop_gradient]
    if frozen:
        raise NotImplementedError(
            f"pipeline blocks with frozen (stop_gradient) params not supported: {frozen}")
    stacked, shardings, decay = [], [], []
    for i in range(n_params):
        arrs = [pb[i]._data for pb in per_block]
        if mesh is not None:
            axes = getattr(per_block[0][i], "logical_axes", None) or (None,) * arrs[0].ndim
            spec = logical_to_spec((None,) + tuple(axes), mesh)
            spec = P(axis_name, *tuple(spec)[1:])
            sh = NamedSharding(mesh, spec)
            # stack under jit with out_shardings so no replicated [L, ...]
            # intermediate is ever materialised in HBM
            st = jax.jit(lambda *a: jnp.stack(a), out_shardings=sh)(*arrs)
            shardings.append(sh)
        else:
            st = jnp.stack(arrs)
            shardings.append(None)
        decay.append(arrs[0].ndim >= 2)
        stacked.append(st)
    return stacked, shardings, names, decay
