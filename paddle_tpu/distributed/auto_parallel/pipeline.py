"""SPMD pipeline parallelism over a ``pp`` mesh axis.

Parity anchor: the reference's dygraph pipeline engine
(/root/reference/python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py:231,
forward_backward_pipeline 1F1B at :547, interleaved VPP at :1143) and its P2P layer
(pp_utils/p2p_communication.py:51 SendRecvMeta shape negotiation).

TPU-native redesign: no per-rank Python schedule, no NCCL P2P, no shape
negotiation. The whole pipeline is ONE jitted SPMD program:

  - layer weights are STACKED along a leading axis sharded over the ``pp`` mesh
    axis — each device materialises only its stage's layers;
  - ``jax.shard_map`` with ``axis_names={"pp"}`` makes only the pp axis manual;
    every other mesh axis (dp/fsdp/tp/sep) stays in GSPMD "auto" mode, so the
    in-stage compute is still sharded by the usual logical-axis rules;
  - activations move between stages with ``lax.ppermute`` (compiles to
    collective-permute riding ICI);
  - the schedule is a ``lax.scan`` over ``n_micro + n_stages - 1`` ticks — the
    GPipe fill/drain pattern. Backward needs no hand-written 1F1B state machine:
    the transpose of ppermute is the reverse rotation, so ``jax.grad`` through
    the scan IS the reverse pipeline schedule. XLA's scheduler overlaps the
    collective-permute with compute (the job NCCL streams did in the reference).

Memory note: GPipe-style stashing of all microbatch activations is avoided by
``remat=True`` (per-block rematerialisation), which is how 1F1B's memory benefit
is obtained in the XLA world.
"""

from __future__ import annotations

import threading
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_state = threading.local()


def in_manual_pipeline() -> bool:
    """True while tracing inside the shard_map(pp) body.

    Layer code that opens its own shard_map (flash attention, ring attention)
    must take the plain auto-sharded path instead — nested manual meshes over
    the same axes are not composable.
    """
    return getattr(_state, "manual", False)


class _ManualCtx:
    def __enter__(self):
        self._prev = in_manual_pipeline()
        _state.manual = True

    def __exit__(self, *exc):
        _state.manual = self._prev
        return False


def gpipe_schedule(stage_fn: Callable, n_stages: int, axis_name: str = "pp",
                   with_aux: bool = False):
    """The GPipe tick schedule, to run INSIDE shard_map where ``axis_name`` is
    manual. ``stage_fn(stage_params, x, *bargs) -> y`` computes one stage
    (``-> (y, aux)`` when ``with_aux``; aux is a scalar summed over active
    ticks and psum'd over stages — MoE load-balance losses ride this).
    Returns ``pipeline(params, micro_inputs, *bargs) -> micro_outputs`` (or
    ``(micro_outputs, aux_total)``) where ``micro_inputs`` is ``[n_micro, ...]``
    (replicated over the pp axis) and the result is psum-replicated from the
    last stage.
    """
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def pipeline(params, micro_in, *bargs):
        n_micro = micro_in.shape[0]
        stage = jax.lax.axis_index(axis_name)
        total_ticks = n_micro + n_stages - 1

        def tick(carry, t):
            buf, outs, aux_acc = carry
            mb_idx = jnp.clip(t, 0, n_micro - 1)
            inject = jax.lax.dynamic_index_in_dim(micro_in, mb_idx, 0, keepdims=False)
            h = jnp.where(stage == 0, inject, buf)
            with _ManualCtx():
                res = stage_fn(params, h, *bargs)
            y, aux = res if with_aux else (res, None)
            if with_aux:
                # bubble ticks run on garbage activations — mask their aux
                active = (t - stage >= 0) & (t - stage < n_micro)
                aux_acc = aux_acc + jnp.where(active, aux, 0.0)
            out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            is_out = (stage == n_stages - 1) & (t >= n_stages - 1)
            prev = jax.lax.dynamic_index_in_dim(outs, out_idx, 0, keepdims=False)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(is_out, y, prev), out_idx, 0)
            nxt = jax.lax.ppermute(y, axis_name, perm)
            return (nxt, outs, aux_acc), None

        buf0 = jnp.zeros(micro_in.shape[1:], micro_in.dtype)
        outs0 = jnp.zeros(micro_in.shape, micro_in.dtype)
        aux0 = jnp.zeros((), jnp.float32)
        (_, outs, aux_acc), _ = jax.lax.scan(
            tick, (buf0, outs0, aux0), jnp.arange(total_ticks))
        # results live on the last stage; zero elsewhere + psum replicates them
        outs = jnp.where(stage == n_stages - 1, outs, jnp.zeros_like(outs))
        outs = jax.lax.psum(outs, axis_name)
        if with_aux:
            return outs, jax.lax.psum(aux_acc, axis_name)
        return outs

    return pipeline


def interleaved_schedule(stage_fn: Callable, n_stages: int, interleave: int,
                         axis_name: str = "pp", with_aux: bool = False):
    """Interleaved virtual-pipeline (VPP) schedule, run INSIDE shard_map.

    Parity anchor: the reference's dygraph interleaved 1F1B
    (fleet/meta_parallel/pipeline_parallel.py:1143 PipelineParallelWithInterleave,
    pp_layers.py get_stage_from_index for the round-robin chunk placement) and
    the static VPP scheduler pass (distributed/passes/pipeline_scheduler_pass).

    TPU-native redesign: each device holds ``v = interleave`` non-adjacent layer
    chunks; every microbatch circulates the pp ring v times, one chunk-hop per
    scan tick. Device d at tick t applies its local chunk
    ``c = ((t - d) mod v*p) // p`` — a traced per-device index into the chunk-
    stacked local params — so the whole interleave is still ONE lax.scan +
    ppermute program and ``jax.grad`` through it is the reverse interleaved
    schedule. Ticks = v*M + p - 1 of chunk-size work (vs GPipe's M + p - 1 of
    stage-size work): bubble fraction drops from (p-1)/(M+p-1) to
    (p-1)/(vM+p-1) — the Megatron-interleave bubble, without a hand-written
    per-rank state machine. Requires M % p == 0 (same constraint as the
    reference: accumulate_steps % pp degree == 0).

    Zero-bubble schedules (ZBH1/ZBVPP, pipeline_scheduler_pass/__init__.py:32)
    split weight-grad from activation-grad compute to fill the drain bubble;
    that decomposition is not expressible through grad-of-scan, so it is
    implemented as a hand-built reverse schedule in :func:`zb_schedule`
    below (select with ``schedule='zb'``; composes with ``interleave`` —
    the ZBVPP shape). This function remains the grad-of-scan path.

    ``stage_fn(local_params, chunk_idx, h, *bargs)`` must apply chunk
    ``chunk_idx`` (local params carry a leading [v] chunk dim).
    """
    p, v = n_stages, interleave
    vp = v * p
    perm = [(i, (i + 1) % p) for i in range(p)]

    def pipeline(params, micro_in, *bargs):
        n_micro = micro_in.shape[0]
        d = jax.lax.axis_index(axis_name)
        total_ticks = v * n_micro + p - 1

        def tick(carry, t):
            buf, outs, aux_acc = carry
            cyc = jnp.mod(t - d, vp)
            c = jnp.clip(cyc // p, 0, v - 1)  # local chunk index this tick
            # device 0, chunk 0: inject microbatch j = (t//vp)*p + t%p
            inj_idx = jnp.clip((t // vp) * p + jnp.mod(t, vp), 0, n_micro - 1)
            inject = jax.lax.dynamic_index_in_dim(micro_in, inj_idx, 0,
                                                  keepdims=False)
            h = jnp.where((d == 0) & (cyc < p), inject, buf)
            with _ManualCtx():
                res = stage_fn(params, c, h, *bargs)
            y, aux = res if with_aux else (res, None)
            # activity mask: entry tick e = t - (c*p + d); real microbatch iff
            # e lands in an injection window and maps to a valid index
            e = t - (c * p + d)
            er = jnp.mod(e, vp)
            mb = (e // vp) * p + er
            active = (e >= 0) & (er < p) & (mb < n_micro)
            if with_aux:
                aux_acc = aux_acc + jnp.where(active, aux, 0.0)
            # device p-1, chunk v-1: final output of microbatch mb
            is_out = (d == p - 1) & (c == v - 1) & active
            out_idx = jnp.clip(mb, 0, n_micro - 1)
            prev = jax.lax.dynamic_index_in_dim(outs, out_idx, 0, keepdims=False)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(is_out, y, prev), out_idx, 0)
            nxt = jax.lax.ppermute(y, axis_name, perm)
            return (nxt, outs, aux_acc), None

        buf0 = jnp.zeros(micro_in.shape[1:], micro_in.dtype)
        outs0 = jnp.zeros(micro_in.shape, micro_in.dtype)
        aux0 = jnp.zeros((), jnp.float32)
        (_, outs, aux_acc), _ = jax.lax.scan(
            tick, (buf0, outs0, aux0), jnp.arange(total_ticks))
        outs = jnp.where(d == p - 1, outs, jnp.zeros_like(outs))
        outs = jax.lax.psum(outs, axis_name)
        if with_aux:
            return outs, jax.lax.psum(aux_acc, axis_name)
        return outs

    return pipeline


def zb_schedule(layer_fn, n_stages: int, interleave: int, lc: int,
                axis_name: str = "pp", bargs=(), remat: bool = False,
                with_aux: bool = False, remat_policy=None):
    """Zero-bubble (ZBH1-class) W/B-split schedule, run INSIDE shard_map.

    Parity anchor: the reference's zero-bubble pipeline passes
    (distributed/passes/pipeline_scheduler_pass/__init__.py:22,36 — ZBH1 /
    ZBVPP, impl pipeline_zero_bubble.py), which split each backward into
    activation-grad (B, on the critical path) and weight-grad (W, deferrable)
    so drain-phase bubbles fill with W work.

    TPU-native redesign (hand-built reverse schedule replacing grad-of-scan):

      1. FWD scan (ticks = vM + p - 1): identical dataflow to the interleaved
         schedule, but every LAYER of the tick's chunk runs under ``jax.vjp``;
         the per-layer pullbacks (linearization residuals) ride out of the
         scans as stacked ys — jax vjp closures are pytrees, so ``lax.scan``
         stacks them.
      2. BWD scan (reverse, same tick count): chains each layer's pullback to
         propagate ONLY the activation cotangent upstream (the weight half of
         each layer's transposed jaxpr is dead code the compiler eliminates),
         reverse-``ppermute``s it, and SAVES the per-layer output cotangents.
         Per-tick critical-path work is B only: the W third of the
         reference's bubble is GONE from both scans.
      3. W drain: one accumulation scan re-applies the saved per-layer
         pullbacks to the saved per-layer cotangents, keeping only the weight
         grads — per-layer deferral exactly like ZBH1's W ops, so no
         activation-chaining is recomputed (each layer's dW is one transpose
         given its own cotangent). No cross-stage dependency — pure local
         matmuls off the permute chain, batched per tick.

    Total critical path ≈ (vM+p-1)(F + B)/v + M·W  vs  the interleaved
    schedule's (vM+p-1)(F + B + W)/v — a saving of W·(p-1)/v wall-clock, the
    exact W-bubble ZBH1 targets.

    Memory regimes (the ZB paper's memory/bubble tradeoff axis):
      - ``remat=False`` (ZB-∞): step 1 saves full linearization residuals
        (incl. the tick's param slice) for every tick — fastest, most memory.
      - ``remat=True, remat_policy=None`` (memory-bounded, ZBH1's regime):
        step 1 saves ONLY each layer's boundary input activation; step 2
        recomputes the layer under ``jax.vjp`` w.r.t. activations only (the
        weight half is never traced); step 3 recomputes once more w.r.t.
        weights only. Memory drops to the boundary-activations class (same
        as GPipe+remat); the extra cost is one more in-layer forward in the
        W drain — which runs OFF the permute critical path, exactly where
        ZBH1 hides work.
      - ``remat=True, remat_policy=<jax.checkpoint policy>`` (selective):
        step 1 runs the vjp over the POLICY-checkpointed layer, so the
        stacked pullbacks hold only the policy-saved residuals (e.g.
        flash_out/flash_lse — backward skips re-running the flash forward
        kernel in BOTH the B scan and the W drain) plus the vjp inputs.
        Memory sits between the other two regimes: the policy-saved tensors
        AND the tick's param slice are stacked per tick (like ZB-∞); for
        models whose per-stage params dwarf activations prefer policy=None.
    Gradient equality vs sequential is exact in all regimes
    (tests/test_pipeline.py).

    ``layer_fn(per_layer_params, h, *bargs)`` runs ONE block (``-> (y,
    aux_scalar)`` when ``with_aux`` — MoE gate losses: the aux sum over
    active ticks is a second differentiable output, and its cotangent enters
    every layer pullback in both the B scan and the W drain). ``bargs`` are
    CLOSED OVER by the custom_vjp (not passed as differentiable arguments):
    rope tables etc. work unchanged, while differentiating w.r.t. a
    broadcast arg raises JAX's closed-over-tracer error at trace time
    instead of silently producing zero gradients.
    """
    p, v = n_stages, interleave
    vp = v * p
    perm_f = [(i, (i + 1) % p) for i in range(p)]
    perm_b = [(i, (i - 1) % p) for i in range(p)]
    # remat regimes: boundary (input-only storage, recompute-twice) vs
    # selective (vjp over the policy-checkpointed layer — pullbacks carry the
    # policy-saved residuals, e.g. flash out/lse, and recompute the rest)
    boundary = remat and remat_policy is None
    selective = remat and remat_policy is not None

    def _chunk(params, c):
        # chunk c's [lc, ...] slice of each local [v*lc, ...] param stack
        return [jax.lax.dynamic_slice_in_dim(w, c * lc, lc, 0)
                for w in params]

    def _fn(wl, h, *b):
        # with_aux: normalize the aux scalar to f32 INSIDE the traced fn so
        # every pullback's aux cotangent is f32 regardless of the block's
        # compute dtype (a bf16 gate under AMP would otherwise reject the
        # f32 g_aux at trace time on zb only)
        res = layer_fn(wl, h, *b)
        if with_aux:
            y, aux = res
            return y, jnp.asarray(aux, jnp.float32)
        return res

    def _meta(t, d, M):
        cyc = jnp.mod(t - d, vp)
        c = jnp.clip(cyc // p, 0, v - 1)  # local chunk index this tick
        e = t - (c * p + d)               # entry tick of this (chunk, device)
        er = jnp.mod(e, vp)
        mb_raw = (e // vp) * p + er
        active = (e >= 0) & (er < p) & (mb_raw < M)
        mb = jnp.clip(mb_raw, 0, M - 1)
        inj_here = (d == 0) & (cyc < p)   # device 0, chunk 0: consumes inject
        inj_idx = jnp.clip((t // vp) * p + jnp.mod(t, vp), 0, M - 1)
        is_out = (d == p - 1) & (c == v - 1) & active
        return c, mb, active, inj_here, inj_idx, is_out

    def _run_fwd(params, micro_in):
        M = micro_in.shape[0]
        d = jax.lax.axis_index(axis_name)
        T = v * M + p - 1

        def ftick(carry, t):
            buf, outs, aux_acc = carry
            c, mb, active, inj_here, inj_idx, is_out = _meta(t, d, M)
            inj = jax.lax.dynamic_index_in_dim(micro_in, inj_idx, 0,
                                               keepdims=False)
            h = jnp.where(inj_here, inj, buf)
            wls = _chunk(params, c)

            if boundary:
                # memory-bounded: stack each layer's INPUT activation only
                def layer_step(carry_l, wl):
                    hh, asum = carry_l
                    res = _fn(wl, hh, *bargs)
                    y, auxl = res if with_aux else (res, 0.0)
                    return (y, asum + auxl), hh
            else:
                # ZB-∞ / selective: stack the per-layer pullback (vjp
                # closures are pytrees, so lax.scan stacks their residuals).
                # Under `selective` the vjp runs over the policy-checkpointed
                # layer, so the pullback carries only policy-saved residuals
                # (flash out/lse etc.) and recomputes the rest when applied.
                vfn = (jax.checkpoint(_fn, policy=remat_policy) if selective
                       else _fn)

                def layer_step(carry_l, wl):
                    hh, asum = carry_l
                    res, pb = jax.vjp(
                        lambda w_, h_: vfn(w_, h_, *bargs), wl, hh)
                    y, auxl = res if with_aux else (res, 0.0)
                    return (y, asum + auxl), pb

            with _ManualCtx():
                (y, tick_aux), pbs_t = jax.lax.scan(
                    layer_step, (h, jnp.zeros((), jnp.float32)), wls)
            if with_aux:
                aux_acc = aux_acc + jnp.where(active, tick_aux, 0.0)
            prev = jax.lax.dynamic_index_in_dim(outs, mb, 0, keepdims=False)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(is_out, y, prev), mb, 0)
            nxt = jax.lax.ppermute(y, axis_name, perm_f)
            return (nxt, outs, aux_acc), pbs_t

        buf0 = jnp.zeros(micro_in.shape[1:], micro_in.dtype)
        outs0 = jnp.zeros(micro_in.shape, micro_in.dtype)
        aux0 = jnp.zeros((), jnp.float32)
        (_, outs, aux_acc), pbs = jax.lax.scan(
            ftick, (buf0, outs0, aux0), jnp.arange(T))
        outs = jnp.where(d == p - 1, outs, jnp.zeros_like(outs))
        outs = jax.lax.psum(outs, axis_name)
        if with_aux:
            return (outs, jax.lax.psum(aux_acc, axis_name)), pbs
        return outs, pbs

    @jax.custom_vjp
    def pipeline(params, micro_in):
        outs, _ = _run_fwd(params, micro_in)
        return outs

    def pipeline_fwd(params, micro_in):
        outs, pbs = _run_fwd(params, micro_in)
        # bargs ride the RESIDUALS: the bwd runs under a different trace than
        # the fwd whose closure captured them (shard_map transpose), so the
        # remat recomputes must read residual-plumbed values, not the closure
        return outs, (pbs, params, bargs)

    def pipeline_bwd(res, g):
        pbs, params, bargs_r = res
        if with_aux:
            g, g_aux = g
            g_aux = jax.lax.psum(jnp.asarray(g_aux, jnp.float32), axis_name)
        else:
            g_aux = None
        # mirror the transpose of the fwd's final psum: shard_map delivers a
        # replicated (P()) output's cotangent split 1/p per device; psumming
        # reconstitutes the full cotangent on every device (exactly what
        # autodiff of `psum(masked_outs)` does in the grad-of-scan schedules)
        g = jax.lax.psum(g, axis_name)
        mshape, mdtype = g.shape, g.dtype  # outs shape/dtype == micro_in's
        M = mshape[0]
        d = jax.lax.axis_index(axis_name)
        T = v * M + p - 1

        # ---- B scan: activation grads only, reverse tick order ----
        def btick(carry, xs):
            gbuf, dmicro = carry
            t, pbs_t = xs
            c, mb, active, inj_here, inj_idx, is_out = _meta(t, d, M)
            g_m = jax.lax.dynamic_index_in_dim(g, mb, 0, keepdims=False)
            dy = jnp.where(is_out, g_m.astype(gbuf.dtype), gbuf)
            dy = jnp.where(active, dy, jnp.zeros_like(dy))
            # aux cotangent: the SAME scalar reaches every active tick's
            # layers (inactive ticks' aux was masked out of the fwd sum)
            daux = (jnp.where(active, g_aux, 0.0) if with_aux else None)

            def _cot(dh):
                return (dh, daux) if with_aux else dh

            if boundary:
                # recompute the layer fwd from its saved INPUT, differentiate
                # w.r.t. activations only (weight half never traced); the
                # INCOMING dh is this layer's output cotangent — saved for W
                wls = _chunk(params, c)

                def layer_bwd(dh, xs_l):
                    hl, wl = xs_l
                    _, pb = jax.vjp(
                        lambda h_: _fn(wl, h_, *bargs_r), hl)
                    (dh2,) = pb(_cot(dh))
                    return dh2, dh

                bxs = (pbs_t, tuple(wls))
            else:
                def layer_bwd(dh, pb):
                    # weight half of pb unused here -> DCE'd from the scan
                    _dw_dead, dh2 = pb(_cot(dh))
                    return dh2, dh

                bxs = pbs_t

            dh, dys_t = jax.lax.scan(layer_bwd, dy, bxs, reverse=True)
            take = inj_here & active
            prev = jax.lax.dynamic_index_in_dim(dmicro, mb, 0, keepdims=False)
            dmicro = jax.lax.dynamic_update_index_in_dim(
                dmicro, jnp.where(take, dh, prev), mb, 0)
            # injected ticks consumed micro_in, not the permuted buf — send
            # nothing upstream for them
            send = jnp.where(inj_here, jnp.zeros_like(dh), dh)
            gnxt = jax.lax.ppermute(send, axis_name, perm_b)
            return (gnxt, dmicro), dys_t

        gbuf0 = jnp.zeros(mshape[1:], mdtype)
        dmicro0 = jnp.zeros(mshape, mdtype)
        (_, dmicro), dys = jax.lax.scan(
            btick, (gbuf0, dmicro0), (jnp.arange(T), pbs), reverse=True)
        # shard_map transposes a replicated (P()) input by psumming per-device
        # cotangents — return only THIS device's contribution
        dmicro = jnp.where(d == 0, dmicro, jnp.zeros_like(dmicro))

        # ---- W drain: per-layer weight grads from saved pullbacks + dys.
        # Iterates only the v*M ACTIVE (chunk, microbatch) pairs — bubble
        # ticks are skipped entirely (the reference's ZB schedules likewise
        # emit W ops per real microbatch only), so the drain is vM ticks of
        # pure W work vs the reverse schedules' T = vM + p - 1.
        def wtick(acc, k):
            c = k // M
            m = k - c * M
            # invert the tick mapping: entry tick of microbatch m on device 0
            # chunk 0 is (m//p)*vp + m%p; this (chunk, device) sees it c*p + d
            # ticks later
            t = (m // p) * vp + jnp.mod(m, p) + c * p + d
            pbs_t = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(a, t, 0,
                                                       keepdims=False), pbs)
            dys_t = jax.lax.dynamic_index_in_dim(dys, t, 0, keepdims=False)

            if boundary:
                # recompute the layer fwd once more from its saved input,
                # differentiate w.r.t. WEIGHTS only — pure local matmuls off
                # the permute chain, exactly the work ZBH1 defers
                wls = _chunk(params, c)

                def layer_w(_, xs_l):
                    hl, dyl, wl = xs_l
                    _, pb = jax.vjp(
                        lambda w_: _fn(w_, hl, *bargs_r), wl)
                    # wtick iterates only ACTIVE pairs -> aux cot = g_aux
                    (dwl,) = pb((dyl, g_aux) if with_aux else dyl)
                    return None, dwl

                wxs = (pbs_t, dys_t, tuple(wls))
            else:
                def layer_w(_, xs_l):
                    pb, dyl = xs_l
                    # activation half unused -> DCE'd
                    dwl, _dh_dead = pb((dyl, g_aux) if with_aux else dyl)
                    return None, dwl

                wxs = (pbs_t, dys_t)

            _, dws = jax.lax.scan(layer_w, None, wxs)
            # scatter-add this tick's [lc]-chunk grads into the local stack
            out = []
            for a, dch in zip(acc, dws):
                cur = jax.lax.dynamic_slice_in_dim(a, c * lc, lc, 0)
                out.append(jax.lax.dynamic_update_slice_in_dim(
                    a, cur + dch.astype(a.dtype), c * lc, 0))
            return tuple(out), None

        dw0 = tuple(jnp.zeros(a.shape, a.dtype) for a in params)
        dw, _ = jax.lax.scan(wtick, dw0, jnp.arange(v * M))
        return dw, dmicro

    pipeline.defvjp(pipeline_fwd, pipeline_bwd)
    return pipeline


def vpp_layer_order(n_layers: int, p: int, v: int):
    """Layer permutation so a contiguous [L/p] slice per device holds its v
    round-robin chunks: device d gets virtual stages {c*p + d}."""
    lc = n_layers // (v * p)
    order = []
    for d in range(p):
        for c in range(v):
            k = c * p + d
            order.extend(range(k * lc, (k + 1) * lc))
    return order


def pipeline_call(
    block_fn: Callable,
    stacked_params: Sequence[jax.Array],
    x: jax.Array,
    *broadcast_args,
    mesh: Mesh,
    n_micro: int,
    axis_name: str = "pp",
    remat: bool = False,
    with_aux: bool = False,
    interleave: int = 1,
    remat_policy=None,
    schedule: str = "auto",
):
    """Run ``x`` through ``n_layers`` stacked blocks, pipelined over ``axis_name``.

    Args:
      block_fn: ``block_fn(per_layer_params, x, *broadcast_args) -> y`` runs ONE
        block (``-> (y, aux_scalar)`` when ``with_aux`` — e.g. MoE gate losses);
        ``per_layer_params`` is a list of arrays without the stacking dim.
      stacked_params: arrays of shape ``[n_layers, ...]``; the leading dim must be
        divisible by the pp axis size (layers are assigned contiguously).
      x: global activations ``[batch, ...]``; batch must divide ``n_micro``.
      broadcast_args: extra per-call inputs replicated to every stage (e.g. rope
        tables).
      n_micro: number of microbatches (the reference's ``accumulate_steps``).
      remat: rematerialise each block in backward (fleet/recompute parity).
      schedule: "auto" (GPipe for interleave=1, interleaved VPP otherwise) or
        "zb" — the zero-bubble W/B-split schedule (see :func:`zb_schedule`;
        ``remat=True`` selects its memory-bounded boundary-storage regime
        (``remat_policy=None``) or the selective policy regime (pullbacks
        keep the policy-saved residuals, e.g. flash out/lse, skipping the
        flash fwd recompute in B and W), ``remat=False`` the ZB-∞
        residual-saving regime; ``broadcast_args``
        are non-differentiable (a grad w.r.t. one raises at trace time);
        ``with_aux`` is supported — MoE gate losses ride the zb schedule).

    Returns global activations with the same shape as ``x`` (plus the aux sum
    over all layers and microbatches when ``with_aux``).
    """
    n_stages = mesh.shape[axis_name]
    if schedule not in ("auto", "zb"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    # zb handles remat itself (boundary-storage when remat_policy is None,
    # selective policy-checkpointed pullbacks otherwise — see zb_schedule);
    # jax.checkpoint wrapping applies to the grad-of-scan schedules only.
    blk = (jax.checkpoint(block_fn, policy=remat_policy)
           if remat and schedule != "zb" else block_fn)

    def _run_layers(wls, h, *bargs):
        # wls: [n_local_layers, ...] arrays; scan blocks over the leading dim
        def body(carry, i):
            h, aux = carry
            wl = [w[i] for w in wls]
            res = blk(wl, h, *bargs)
            if with_aux:
                y, a = res
                return (y, aux + a), None
            return (res, aux), None

        (h, aux), _ = jax.lax.scan(
            body, (h, jnp.zeros((), jnp.float32)), jnp.arange(wls[0].shape[0]))
        return (h, aux) if with_aux else h

    def stage_fn(local_params, h, *bargs):
        return _run_layers(local_params, h, *bargs)

    if n_stages == 1:
        return stage_fn(list(stacked_params), x, *broadcast_args)

    batch = x.shape[0]
    if batch % n_micro != 0:
        raise ValueError(f"batch {batch} not divisible by n_micro {n_micro}")
    mb = batch // n_micro
    micro = x.reshape((n_micro, mb) + x.shape[1:])

    if interleave > 1 or schedule == "zb":
        n_layers = stacked_params[0].shape[0]
        if n_layers % (interleave * n_stages) != 0:
            raise ValueError(
                f"n_layers {n_layers} not divisible by interleave*pp "
                f"{interleave}*{n_stages}")
        if interleave > 1 and n_micro % n_stages != 0:
            raise ValueError(
                f"VPP requires n_micro % pp == 0, got {n_micro} % {n_stages} "
                f"(reference: accumulate_steps % pp_degree == 0)")
        lc = n_layers // (interleave * n_stages)

        def chunk_stage_fn(local_params, c, h, *bargs):
            # local [v*lc, ...] -> select chunk c's [lc, ...] slice
            wls = [jax.lax.dynamic_slice_in_dim(w, c * lc, lc, 0)
                   for w in local_params]
            return _run_layers(wls, h, *bargs)

    if schedule == "zb":
        def pipeline(params, micro_in, *bargs):
            # bargs are closed over by the zb custom_vjp: differentiating
            # w.r.t. them raises at trace time (vs. silent zero cotangents)
            zb = zb_schedule(blk, n_stages, interleave, lc, axis_name,
                             bargs=bargs, remat=remat, with_aux=with_aux,
                             remat_policy=remat_policy)
            return zb(params, micro_in)
    elif interleave > 1:
        pipeline = interleaved_schedule(
            chunk_stage_fn, n_stages, interleave, axis_name, with_aux=with_aux)
    else:
        pipeline = gpipe_schedule(stage_fn, n_stages, axis_name, with_aux=with_aux)
    n_params = len(stacked_params)
    out_specs = (P(), P()) if with_aux else P()
    from ...framework.jax_compat import shard_map

    smapped = shard_map(
        pipeline,
        mesh=mesh,
        in_specs=(tuple(P(axis_name) for _ in range(n_params)), P())
        + tuple(P() for _ in broadcast_args),
        out_specs=out_specs,
        axis_names=frozenset({axis_name}),
        check_vma=False,
    )
    res = smapped(tuple(stacked_params), micro, *broadcast_args)
    if with_aux:
        out, aux = res
        return out.reshape(x.shape), aux
    return res.reshape(x.shape)


def stack_block_params(blocks, mesh=None, axis_name: str = "pp",
                       interleave: int = 1):
    """Stack per-block parameter Tensors into ``[n_layers, ...]`` arrays.

    Returns (stacked_arrays, shardings, names, decay_mask). All blocks must have
    identical parameter structure (true for transformer decoder stacks). The
    leading dim is sharded over ``axis_name``; trailing dims follow each param's
    logical axes — so pp composes with fsdp/tp sharding of the weights
    (the reference's PP×sharding×MP hybrid, fleet/base/topology.py:70).

    With ``interleave=v > 1`` the layers are stacked in ``vpp_layer_order`` so
    each device's contiguous slice holds its v round-robin virtual-stage chunks
    (cf. pp_layers.py get_stage_from_index interleaved placement).
    """
    from jax.sharding import NamedSharding
    from .logical_sharding import logical_to_spec

    if interleave > 1 and mesh is not None:
        order = vpp_layer_order(len(blocks), mesh.shape[axis_name], interleave)
        blocks = [blocks[i] for i in order]
    else:
        order = list(range(len(blocks)))
    per_block = [[t for _, t in b.named_parameters()] for b in blocks]
    names = [n for n, _ in blocks[0].named_parameters()]
    n_params = len(per_block[0])
    for pb in per_block:
        if len(pb) != n_params:
            raise ValueError("pipeline blocks have differing parameter structure")
    frozen = [n for n, t in blocks[0].named_parameters() if t.stop_gradient]
    if frozen:
        raise NotImplementedError(
            f"pipeline blocks with frozen (stop_gradient) params not supported: {frozen}")
    stacked, shardings, decay = [], [], []
    for i in range(n_params):
        arrs = [pb[i]._data for pb in per_block]
        if mesh is not None:
            axes = getattr(per_block[0][i], "logical_axes", None) or (None,) * arrs[0].ndim
            spec = logical_to_spec((None,) + tuple(axes), mesh)
            spec = P(axis_name, *tuple(spec)[1:])
            sh = NamedSharding(mesh, spec)
            # stack under jit with out_shardings so no replicated [L, ...]
            # intermediate is ever materialised in HBM
            st = jax.jit(lambda *a: jnp.stack(a), out_shardings=sh)(*arrs)
            shardings.append(sh)
        else:
            st = jnp.stack(arrs)
            shardings.append(None)
        decay.append(arrs[0].ndim >= 2)
        stacked.append(st)
    return stacked, shardings, names, decay, order
