"""Placements (reference: phi/core/distributed/auto_parallel/placement_types.h).

Shard(d)/Replicate()/Partial() describe how a logical tensor maps onto mesh axes;
they translate directly to a ``PartitionSpec``: the i-th placement names what the
i-th MESH axis does (shard tensor dim d / replicate / hold partial sums).
"""

from __future__ import annotations

from typing import List, Sequence

from jax.sharding import NamedSharding, PartitionSpec


class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicate(self):
        return False

    def is_partial(self):
        return False


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = dim

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def get_dim(self):
        return self.dim

    def __eq__(self, o):
        return isinstance(o, Shard) and o.dim == self.dim

    def __hash__(self):
        return hash(("shard", self.dim))

    def __repr__(self):
        return f"Shard(dim={self.dim})"


class Replicate(Placement):
    def is_replicate(self):
        return True

    def __eq__(self, o):
        return isinstance(o, Replicate)

    def __hash__(self):
        return hash("replicate")

    def __repr__(self):
        return "Replicate()"


class Partial(Placement):
    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __eq__(self, o):
        return isinstance(o, Partial) and o.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("partial", self.reduce_type))

    def __repr__(self):
        return f"Partial({self.reduce_type})"


def placements_to_spec(placements: Sequence[Placement], mesh_dim_names: Sequence[str], ndim: int) -> PartitionSpec:
    """Convert per-mesh-axis placements into a tensor-dim PartitionSpec."""
    entries: List = [None] * ndim
    for axis_name, p in zip(mesh_dim_names, placements):
        if isinstance(p, Shard):
            d = p.dim % ndim
            if entries[d] is None:
                entries[d] = axis_name
            elif isinstance(entries[d], tuple):
                entries[d] = entries[d] + (axis_name,)
            else:
                entries[d] = (entries[d], axis_name)
    while entries and entries[-1] is None:
        entries.pop()
    return PartitionSpec(*entries)


def spec_to_placements(spec: PartitionSpec, mesh_dim_names: Sequence[str], ndim: int) -> List[Placement]:
    out: List[Placement] = [Replicate() for _ in mesh_dim_names]
    entries = list(spec) if spec is not None else []
    for tdim, e in enumerate(entries):
        if e is None:
            continue
        axes = e if isinstance(e, tuple) else (e,)
        for a in axes:
            out[mesh_dim_names.index(a)] = Shard(tdim)
    return out
