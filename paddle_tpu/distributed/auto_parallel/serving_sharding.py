"""Column-parallel tensor-parallel context for the SERVING hot paths.

The training side shards with GSPMD (logical_sharding.py: annotate params,
pick a mesh, let XLA insert collectives). The serving engine cannot use
that recipe: its identity contract — greedy token streams bit-equal to the
single-device engine — rules out any collective that REDUCES across shards
(a psum reassociates the contraction sum, which moves the last ulp, which
can flip an argmax near a tie). So the serving mesh path is built from
``shard_map`` with a single discipline:

    every tp-sharded weight is split along its OUTPUT dimension, so each
    output element is computed WHOLE on exactly one device with the full
    contraction in its original order; the only collectives are
    ``all_gather``s of disjoint shards — pure data movement, bit-exact.

Concretely (llama): q/k/v projections shard along heads, gate/up along
mlp, lm_head along vocab; o_proj/down_proj/embedding/norms stay
replicated, and the sharded activations are all-gathered right before the
weights that contract over them. KV pools shard along kv_heads to match
the k/v projections, so paged appends and decode attention are
shard-local — the pool is never resharded between steps.

This module is the trace-time channel telling model code it is INSIDE such
a shard_map region and which mesh axis to gather over. Layers call
:func:`gather_output_shards` at the three gather sites (attention output,
mlp activation, logits); outside a serving shard context it is a no-op, so
the same model code serves the single-device engine, GSPMD training, and
the sharded serving programs.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

__all__ = ["serving_tp_axis", "serving_shard_axis", "gather_output_shards",
           "harvest_param_shards", "adopt_resharded_params"]

_state = threading.local()


def serving_tp_axis() -> Optional[str]:
    """The mesh axis name of the enclosing serving shard_map region, or
    None when tracing/running outside one (the common, unsharded case)."""
    return getattr(_state, "axis", None)


@contextlib.contextmanager
def serving_shard_axis(axis: Optional[str]):
    """Mark the dynamic extent of a serving shard_map body. The engine
    wraps each sharded hot-path program's trace in this; model code reads
    it through :func:`serving_tp_axis` / :func:`gather_output_shards`."""
    prev = serving_tp_axis()
    _state.axis = axis
    try:
        yield
    finally:
        _state.axis = prev


def gather_output_shards(x):
    """All-gather ``x``'s LAST dim across the serving tp axis (tiled), or
    return ``x`` unchanged outside a serving shard context.

    The callee computed ``x`` column-sharded — each element whole on one
    device — so the gather is an exact concatenation: the full array is
    bit-identical to what a single device would have computed."""
    axis = serving_tp_axis()
    if axis is None:
        return x
    import jax

    return jax.lax.all_gather(x, axis, axis=x.ndim - 1, tiled=True)


# ---------------------------------------------------------------------------
# elastic reshard plan (MeshDegraded / PT-SRV-008 — docs/RESILIENCE.md
# "Elastic serving mesh")
# ---------------------------------------------------------------------------

def harvest_param_shards(engine):
    """Gather a (possibly degraded) engine's weights host-side, ONCE.

    Because every tp-sharded weight is column-parallel — disjoint shards
    along the output dim, no partial sums — gathering is an exact
    concatenation: the host arrays are bit-identical to the unsharded
    weights regardless of the width they were serving at. This is the
    first half of the elastic degrade reshard plan: harvest at the old
    width, rebuild the engine at the surviving width, then
    :func:`adopt_resharded_params` re-splits the SAME bytes along the
    SAME output dims.

    Returns a list of host (numpy) arrays in ``engine._params`` order."""
    import numpy as np

    return [np.asarray(p) for p in engine._params]


def adopt_resharded_params(engine, host_params):
    """Re-slab harvested weights onto a rebuilt engine's mesh.

    ``host_params`` must be :func:`harvest_param_shards` output from an
    engine built over the same model (same param order and shapes). Each
    array is re-placed per the NEW engine's per-param specs — column
    shards along the same output dims at the surviving tp width, or plain
    committed arrays when the rebuild fell back to unsharded. Returns the
    engine (weights swapped in place)."""
    import jax
    import jax.numpy as jnp

    if len(host_params) != len(engine._params):
        raise ValueError(
            f"reshard plan mismatch: {len(host_params)} harvested param(s) "
            f"vs {len(engine._params)} in the rebuilt engine — the degrade "
            f"rebuild must reuse the same model")
    mesh = getattr(engine, "_mesh", None)
    if mesh is None:
        engine._params = [jnp.asarray(p) for p in host_params]
        return engine
    from jax.sharding import NamedSharding

    engine._params = [
        jax.device_put(p, NamedSharding(mesh, s))
        for p, s in zip(host_params, engine._param_specs)]
    return engine
