"""Column-parallel tensor-parallel context for the SERVING hot paths.

The training side shards with GSPMD (logical_sharding.py: annotate params,
pick a mesh, let XLA insert collectives). The serving engine cannot use
that recipe: its identity contract — greedy token streams bit-equal to the
single-device engine — rules out any collective that REDUCES across shards
(a psum reassociates the contraction sum, which moves the last ulp, which
can flip an argmax near a tie). So the serving mesh path is built from
``shard_map`` with a single discipline:

    every tp-sharded weight is split along its OUTPUT dimension, so each
    output element is computed WHOLE on exactly one device with the full
    contraction in its original order; the only collectives are
    ``all_gather``s of disjoint shards — pure data movement, bit-exact.

Concretely (llama): q/k/v projections shard along heads, gate/up along
mlp, lm_head along vocab; o_proj/down_proj/embedding/norms stay
replicated, and the sharded activations are all-gathered right before the
weights that contract over them. KV pools shard along kv_heads to match
the k/v projections, so paged appends and decode attention are
shard-local — the pool is never resharded between steps.

This module is the trace-time channel telling model code it is INSIDE such
a shard_map region and which mesh axis to gather over. Layers call
:func:`gather_output_shards` at the three gather sites (attention output,
mlp activation, logits); outside a serving shard context it is a no-op, so
the same model code serves the single-device engine, GSPMD training, and
the sharded serving programs.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

__all__ = ["serving_tp_axis", "serving_shard_axis", "gather_output_shards"]

_state = threading.local()


def serving_tp_axis() -> Optional[str]:
    """The mesh axis name of the enclosing serving shard_map region, or
    None when tracing/running outside one (the common, unsharded case)."""
    return getattr(_state, "axis", None)


@contextlib.contextmanager
def serving_shard_axis(axis: Optional[str]):
    """Mark the dynamic extent of a serving shard_map body. The engine
    wraps each sharded hot-path program's trace in this; model code reads
    it through :func:`serving_tp_axis` / :func:`gather_output_shards`."""
    prev = serving_tp_axis()
    _state.axis = axis
    try:
        yield
    finally:
        _state.axis = prev


def gather_output_shards(x):
    """All-gather ``x``'s LAST dim across the serving tp axis (tiled), or
    return ``x`` unchanged outside a serving shard context.

    The callee computed ``x`` column-sharded — each element whole on one
    device — so the gather is an exact concatenation: the full array is
    bit-identical to what a single device would have computed."""
    axis = serving_tp_axis()
    if axis is None:
        return x
    import jax

    return jax.lax.all_gather(x, axis, axis=x.ndim - 1, tiled=True)
