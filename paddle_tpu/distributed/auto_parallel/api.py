"""Semi-automatic parallel API (reference: python/paddle/distributed/auto_parallel/api.py).

TPU-native: a "DistTensor" is a jax.Array carrying a NamedSharding — GSPMD replaces
the reference's DistTensor + ~60 SPMD infer rules + 11 reshard functions
(phi/core/distributed/auto_parallel/): sharding propagation happens in the XLA
compiler; ``reshard`` is ``device_put``/``with_sharding_constraint`` (collective
chosen by XLA: all-gather for s→r, dynamic-slice for r→s, reduce for partial, …).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ...core.tensor import Parameter, Tensor
from ...nn.layer.layers import Layer
from .placement import Partial, Placement, Replicate, Shard, placements_to_spec, spec_to_placements
from .process_mesh import ProcessMesh


def _named_sharding(mesh: ProcessMesh, placements: Sequence[Placement], ndim: int) -> NamedSharding:
    spec = placements_to_spec(placements, mesh.dim_names, ndim)
    return NamedSharding(mesh.to_jax(), spec)


def _validate_placements(shape, mesh, placements):
    """Pre-lowering SPMD consistency check (static/analysis): an invalid axis
    or uneven shard diagnosed HERE has a name; at pjit time it is an opaque
    XLA sharding error or a silent dim-wrap."""
    import warnings

    # submodule import on purpose: spmd_check is dependency-light; pulling
    # the whole analysis package here would defeat its lazy loading
    from ...static.analysis.spmd_check import check_placements

    for d in check_placements(shape, mesh, placements):
        warnings.warn(d.format(), UserWarning, stacklevel=3)


def shard_tensor(data, mesh: ProcessMesh, placements: Sequence[Placement], dtype=None, stop_gradient=None):
    """Place a tensor onto a mesh with given placements (reference api.py:181)."""
    t = data if isinstance(data, Tensor) else Tensor(data, dtype=dtype)
    _validate_placements(tuple(t._data.shape), mesh, placements)
    sharding = _named_sharding(mesh, placements, t._data.ndim)
    arr = t._data
    if isinstance(arr, jax.core.Tracer):
        arr = jax.lax.with_sharding_constraint(arr, sharding)
    else:
        arr = jax.device_put(arr, sharding)
    t._data = arr
    t.process_mesh = mesh
    t.placements = list(placements)
    if stop_gradient is not None:
        t.stop_gradient = stop_gradient
    return t


def dtensor_from_local(local_tensor, mesh, placements):
    # single-controller: the local tensor IS the global view on 1 process
    return shard_tensor(local_tensor, mesh, placements)


def dtensor_to_local(dist_tensor, mesh=None, placements=None):
    arr = dist_tensor._data
    shards = getattr(arr, "addressable_shards", None)
    if shards:
        return Tensor(shards[0].data)
    return dist_tensor


def reshard(dist_tensor: Tensor, mesh: ProcessMesh, placements: Sequence[Placement]):
    """Change placements (reference api.py:677) — XLA inserts the collective."""
    # Partial -> Replicate needs an explicit reduction in eager single-controller mode
    old = getattr(dist_tensor, "placements", None)
    arr = dist_tensor._data
    if old is not None and any(p.is_partial() for p in old):
        # sum over the partial mesh axes: in SPMD global view the array already holds
        # the partial contribution of each shard summed? No — partial only arises
        # inside shard_map; at global view we materialize via psum there. Here it is
        # a no-op annotation change.
        pass
    sharding = _named_sharding(mesh, placements, arr.ndim)
    if isinstance(arr, jax.core.Tracer):
        new = jax.lax.with_sharding_constraint(arr, sharding)
    else:
        new = jax.device_put(arr, sharding)
    out = Tensor(new, stop_gradient=dist_tensor.stop_gradient)
    out._node, out._out_idx = dist_tensor._node, dist_tensor._out_idx
    out.process_mesh = mesh
    out.placements = list(placements)
    return out


def shard_layer(layer: Layer, process_mesh: ProcessMesh, shard_fn=None, input_fn=None, output_fn=None) -> Layer:
    """Shard a layer's parameters across a mesh (reference api.py:778)."""
    if shard_fn is None:
        def shard_fn(name, sublayer, mesh):
            for pname, p in sublayer._parameters.items():
                if p is not None:
                    shard_tensor(p, mesh, [Replicate() for _ in mesh.dim_names])

    for name, sub in layer.named_sublayers(include_self=True):
        shard_fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(lambda l, inp: input_fn(inp, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(lambda l, inp, out: output_fn(out, process_mesh))
    return layer


def shard_optimizer(optimizer, shard_fn=None, gradient_accumulation_steps=1):
    """ZeRO-style optimizer-state sharding (reference api.py:1486): accumulator
    arrays inherit (or shard_fn overrides) the parameter's sharding; XLA keeps
    update math local to each shard."""
    orig_acc = optimizer._acc

    def _acc(name, p, init=None, dtype=None):
        arr = orig_acc(name, p, init, dtype)
        sharding = getattr(p._data, "sharding", None)
        if shard_fn is not None:
            arr2 = shard_fn(name, p, Tensor(arr))
            if arr2 is not None:
                arr = arr2._data if isinstance(arr2, Tensor) else arr2
                optimizer._accumulators[name][id(p)] = arr
        elif sharding is not None and not isinstance(arr, jax.core.Tracer) and arr.ndim == p._data.ndim:
            arr = jax.device_put(arr, sharding)
            optimizer._accumulators[name][id(p)] = arr
        return arr

    optimizer._acc = _acc
    return optimizer


class ShardingStage1:
    """Marker strategies matching reference paddle.distributed.ShardingStage* for
    shard_optimizer(shard_fn=...)."""

    def __init__(self, axis_name="dp", mesh=None):
        self.axis_name = axis_name
        self.mesh = mesh

    def __call__(self, key, param, acc):
        mesh = self.mesh or getattr(param, "process_mesh", None)
        if mesh is None:
            return acc
        ndim = acc._data.ndim if isinstance(acc, Tensor) else acc.ndim
        placements = [Shard(0) if n == self.axis_name and ndim > 0 else Replicate() for n in mesh.dim_names]
        return shard_tensor(acc, mesh, placements)


ShardingStage2 = ShardingStage1


class ShardingStage3(ShardingStage1):
    pass


def unshard_dtensor(dist_tensor):
    arr = dist_tensor._data
    full_sharding = NamedSharding(
        getattr(dist_tensor, "process_mesh").to_jax() if hasattr(dist_tensor, "process_mesh") else arr.sharding.mesh,
        PartitionSpec(),
    )
    return Tensor(jax.device_put(arr, full_sharding))


def get_mesh():
    from .process_mesh import get_current_mesh

    return get_current_mesh()


def set_mesh(mesh):
    from .process_mesh import _mesh_stack

    _mesh_stack.clear()
    _mesh_stack.append(mesh)


# ---- distributed dataloader ----
def shard_dataloader(dataloader, meshes, shard_dims=None, input_keys=None):
    """Reference api.py:2990 — wrap a loader so each batch lands sharded on the mesh."""
    mesh = meshes[0] if isinstance(meshes, (list, tuple)) else meshes
    dim = shard_dims if isinstance(shard_dims, str) else (shard_dims[0] if shard_dims else None)

    class _ShardedLoader:
        def __init__(self, inner):
            self._inner = inner

        def __iter__(self):
            for batch in self._inner:
                yield self._shard(batch)

        def __len__(self):
            return len(self._inner)

        def _shard(self, item):
            if isinstance(item, Tensor):
                placements = [
                    Shard(0) if (dim is None or n == dim) and item.ndim > 0 else Replicate()
                    for n in mesh.dim_names
                ]
                if dim is not None:
                    placements = [Shard(0) if n == dim else Replicate() for n in mesh.dim_names]
                return shard_tensor(item, mesh, placements)
            if isinstance(item, (list, tuple)):
                return type(item)(self._shard(i) for i in item)
            if isinstance(item, dict):
                return {k: self._shard(v) for k, v in item.items()}
            return item

    return _ShardedLoader(dataloader)


class Strategy:
    """Reference: auto_parallel/strategy.py — config tree for to_static engine."""

    class _Cfg:
        def __init__(self, **kw):
            self.__dict__.update(kw)

    def __init__(self, config=None):
        self.sharding = Strategy._Cfg(enable=False, degree=1, stage=1)
        self.fused_passes = Strategy._Cfg(enable=False, fused_passes_list=[])
        self.pipeline = Strategy._Cfg(enable=False, schedule_mode="1F1B", micro_batch_size=1, accumulate_steps=1)
        self.amp = Strategy._Cfg(enable=False, dtype="bfloat16", level="O1")
        self.recompute = Strategy._Cfg(enable=False)
        if config:
            for k, v in config.items():
                if hasattr(self, k) and isinstance(v, dict):
                    getattr(self, k).__dict__.update(v)


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None, input_spec=None):
    """Reference api.py:2484 — returns a DistModel-style wrapper running the jitted step."""
    from ...hapi.model import Model

    m = Model(layer)
    m.prepare(optimizer=optimizer, loss=loss, jit=True)

    class DistModel:
        def __init__(self):
            self.network = layer
            self._model = m
            self._mode = "train"

        def train(self):
            self._mode = "train"
            layer.train()

        def eval(self):
            self._mode = "eval"
            layer.eval()

        def __call__(self, *args):
            if self._mode == "train":
                inputs, labels = list(args[:-1]), [args[-1]]
                losses, _ = self._model.train_batch(inputs, labels)
                return Tensor(jnp.asarray(losses[0]))
            return layer(*args)

        def state_dict(self):
            return layer.state_dict()

    return DistModel()
