"""Collective-contract programs for the PT-COMM auditor (ROADMAP item 1).

One compact Megatron/FSDP-style train step whose EXPLICIT collectives
spell out the placement contract each recorded MULTICHIP mesh shape
implies — the artifact tools/audit_collectives.py traces under a
symbolic ``AbstractMesh`` (no devices, no XLA compile) and baselines in
tools/collective_baseline.json. The real sharded serving/training work
(item 1) inherits these as ratchets: the per-axis collective kinds,
counts and ring wire bytes recorded here are the contract its programs
must meet.

The step adapts to whichever axes the mesh declares (size-1 axes are
dropped):

- ``dp``            data parallel: gradient ``psum``
- ``fsdp``          ZeRO-3: params ``all_gather`` before use, gradients
                    ``psum_scatter`` back to shards (+ batch sharding)
- ``tp``            Megatron tensor parallel: column-parallel w1, row-
                    parallel w2, forward/backward partial-sum ``psum``
- ``sep``           Ulysses sequence parallel: ``all_to_all`` seq<->
                    feature around the sequence mixer (+ grad ``psum``)
- ``ep``            MoE expert parallel: ``global_scatter``/
                    ``global_gather`` token ``all_to_all`` dispatch
                    (+ batch sharding, grad ``psum``)
- ``pp``            pipeline: one boundary ``ppermute`` each direction

The backward pass is written out by hand (transposed matmuls) rather
than via ``jax.grad`` so the collective plan is explicit and readable —
this is a CONTRACT program: the auditor censuses what it dispatches, it
never executes.
"""

from __future__ import annotations

from typing import Dict, Tuple

__all__ = ["train_step_comm", "moe_combine_comm"]


def _prod(xs):
    n = 1
    for x in xs:
        n *= x
    return n


def train_step_comm(mesh_axes: Dict[str, int], *, batch_per_shard: int = 2,
                    seq_per_shard: int = 8, d_model: int = 32,
                    d_hidden: int = 64, dtype="bfloat16"):
    """Build the contract step for one mesh shape. Returns
    ``(fn, input_structs, input_names, axes)`` ready for
    ``trace_to_program`` — ``fn`` is the shard_map'd step over GLOBAL
    shapes, ``axes`` the normalized (size>1) mesh dict."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from ...framework.jax_compat import shard_map
    from ...static.comm.mesh import abstract_mesh, mesh_spec
    from ..utils.moe_utils import global_gather, global_scatter

    axes = {k: int(v) for k, v in mesh_axes.items() if int(v) > 1}
    if not axes:
        raise ValueError("train_step_comm needs at least one >1 mesh axis")
    dp, fsdp, tp = axes.get("dp", 1), axes.get("fsdp", 1), axes.get("tp", 1)
    sep, pp, ep = axes.get("sep", 1), axes.get("pp", 1), axes.get("ep", 1)
    # batch shards over every data-like axis present (fsdp = ZeRO data
    # parallelism; ep ranks own disjoint token sets pre-dispatch)
    data_axes = tuple(a for a in ("dp", "fsdp", "ep") if a in axes)
    B = batch_per_shard * _prod(axes[a] for a in data_axes)
    S = seq_per_shard * sep
    D, H = d_model, d_hidden
    assert D % max(sep, 1) == 0 and D % max(fsdp, 1) == 0
    assert H % max(tp, 1) == 0
    grad_sum_axes = tuple(a for a in ("dp", "ep", "sep") if a in axes)
    np_dtype = np.dtype(dtype)

    def step(w1, w2, x, y):
        # local shapes: w1 [D/fsdp, H/tp], w2 [H/tp, D/fsdp],
        # x/y [batch_per_shard, seq_per_shard, D]
        w1f, w2f = w1, w2
        if fsdp > 1:      # ZeRO-3: unshard params for the step's compute
            w1f = lax.all_gather(w1, "fsdp", axis=0, tiled=True)
            w2f = lax.all_gather(w2, "fsdp", axis=1, tiled=True)
        xs = x
        if sep > 1:       # Ulysses: seq<->feature exchange, mix, invert
            xs = lax.all_to_all(xs, "sep", split_axis=2, concat_axis=1,
                                tiled=True)               # [b, S, D/sep]
            xs = jax.nn.softmax(xs, axis=1) * xs          # global-seq mixer
            xs = lax.all_to_all(xs, "sep", split_axis=1, concat_axis=2,
                                tiled=True)               # [b, s, D]
        b, s = xs.shape[0], xs.shape[1]
        t = xs.reshape(b * s, D)
        if ep > 1:        # MoE: token dispatch to expert ranks
            t = global_scatter(t, axis_name="ep")
        h = jax.nn.relu(t @ w1f)                          # [T, H/tp] col-par
        o = h @ w2f                                       # [T, D] partial
        if tp > 1:
            o = lax.psum(o, "tp")                         # row-parallel fwd
        if ep > 1:
            o = global_gather(o, axis_name="ep")
            td = t                                        # dispatched tokens
        o = o.reshape(b, s, D)
        if pp > 1:        # stage boundary: activations forward
            o = lax.ppermute(o, "pp", [(i, (i + 1) % pp) for i in range(pp)])
        e = (o - y.astype(o.dtype)) * np_dtype.type(1.0 / (B * S * D))
        if pp > 1:        # stage boundary: error backward
            e = lax.ppermute(e, "pp", [(i, (i - 1) % pp) for i in range(pp)])
        et = e.reshape(b * s, D)
        if ep > 1:        # backward of global_gather = dispatch the error
            et = global_scatter(et, axis_name="ep")
            t = td
        gw2 = h.T @ et                                    # [H/tp, D]
        gh = (et @ w2f.T) * (h > 0).astype(h.dtype)       # [T, H/tp]
        gw1 = t.T @ gh                                    # [D, H/tp]
        gt = gh @ w1f.T                                   # [T, D] partial
        if tp > 1:
            gt = lax.psum(gt, "tp")                       # col-parallel bwd
        for a in grad_sum_axes:                           # data-axis sync
            gw1 = lax.psum(gw1, a)
            gw2 = lax.psum(gw2, a)
        if fsdp > 1:      # ZeRO-3: reduce gradients back to param shards
            gw1 = lax.psum_scatter(gw1, "fsdp", scatter_dimension=0,
                                   tiled=True)
            gw2 = lax.psum_scatter(gw2, "fsdp", scatter_dimension=1,
                                   tiled=True)
        loss = et.sum() + gt.sum() * np_dtype.type(0)
        for a in grad_sum_axes:
            loss = lax.psum(loss, a)
        lr = np_dtype.type(1e-3)
        return w1 - lr * gw1, w2 - lr * gw2, loss

    mesh = abstract_mesh(axes)
    w1_spec = mesh_spec(axes, "fsdp", "tp")
    w2_spec = mesh_spec(axes, "tp", "fsdp")
    act_spec = mesh_spec(axes, data_axes or None, "sep", None)
    fn = shard_map(step, mesh=mesh,
                   in_specs=(w1_spec, w2_spec, act_spec, act_spec),
                   out_specs=(w1_spec, w2_spec, mesh_spec(axes)),
                   check_vma=False)
    sd = jax.ShapeDtypeStruct
    structs = (sd((D, H), np_dtype), sd((H, D), np_dtype),
               sd((B, S, D), np_dtype), sd((B, S, D), np_dtype))
    return fn, structs, ["w1", "w2", "x", "y"], axes


def moe_combine_comm(ep: int, *, tokens_per_rank: int = 16,
                     d_model: int = 16, dtype="bfloat16"
                     ) -> Tuple[object, tuple, list, Dict[str, int]]:
    """The MoE dispatch/combine spmd-rule program (SURVEY catalogue
    ``moe_combine``): ``global_scatter`` -> per-rank expert FFN ->
    ``global_gather``, the two token ``all_to_all``s every expert-
    parallel step pays. Same return contract as
    :func:`train_step_comm`."""
    import jax
    import numpy as np
    from jax import nn as jnn
    from jax.sharding import PartitionSpec as P

    from ...framework.jax_compat import shard_map
    from ...static.comm.mesh import abstract_mesh
    from ..utils.moe_utils import global_gather, global_scatter

    ep = int(ep)
    if tokens_per_rank % ep:
        raise ValueError("tokens_per_rank must divide the ep width")
    np_dtype = np.dtype(dtype)
    D = d_model

    def combine(x, we):
        xd = global_scatter(x, axis_name="ep")   # tokens -> expert ranks
        h = jnn.relu(xd @ we)                    # this rank's expert(s)
        return global_gather(h, axis_name="ep")  # tokens -> home ranks

    mesh = abstract_mesh({"ep": ep})
    fn = shard_map(combine, mesh=mesh,
                   in_specs=(P("ep", None), P(None, None)),
                   out_specs=P("ep", None), check_vma=False)
    sd = jax.ShapeDtypeStruct
    structs = (sd((ep * tokens_per_rank, D), np_dtype),
               sd((D, D), np_dtype))
    return fn, structs, ["tokens", "w_expert"], {"ep": ep}
