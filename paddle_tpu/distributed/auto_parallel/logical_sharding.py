"""Logical-axis sharding rules — the TPU-native replacement for the reference's
per-op SPMD rules (`/root/reference/paddle/phi/infermeta/spmd_rules/*`, ~60 files).

Instead of propagating placements op-by-op in C++, model code names each parameter
and activation dimension with a *logical* axis ("embed", "heads", "mlp", "vocab",
"batch", "seq"); a rules table maps logical axes onto physical mesh axes
("dp", "fsdp", "sep", "tp", "pp", "ep"). GSPMD then propagates shardings through
the whole jitted program and inserts the collectives (the job of the reference's
reshard functions, `phi/core/distributed/auto_parallel/reshard/*`).

This is the scaling-book recipe: pick a mesh, annotate, let XLA insert collectives.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Default rules: logical axis -> mesh axis (or tuple of mesh axes).
# Mirrors the reference's hybrid topology axes [data, pipe, sharding, sep, model]
# (fleet/base/topology.py:70) mapped to a TPU mesh ("dp","fsdp","sep","tp") + "ep".
DEFAULT_RULES = (
    ("batch", ("dp", "fsdp")),
    ("seq", "sep"),
    ("vocab", "tp"),
    # input-embedding vocab dim: left unsharded — a tp-sharded lookup table turns
    # jnp.take into a full-rematerialization gather under GSPMD; the table is
    # still fsdp-sharded along "embed".
    ("vocab_in", None),
    ("embed", "fsdp"),
    ("heads", "tp"),
    ("kv_heads", "tp"),
    ("mlp", "tp"),
    ("expert", "ep"),
    ("expert_mlp", "tp"),
    ("head_dim", None),
    ("norm", None),
    # Megatron-SP residual stream: sequence sharded over the TP group too
    # (fleet/utils/sequence_parallel_utils.py ScatterOp/GatherOp semantics) —
    # GSPMD inserts the all-gather before qkv/mlp projections and the
    # reduce-scatter after the row-parallel matmuls
    ("seq_sp", ("sep", "tp")),
)

_state = threading.local()


def current_mesh() -> Optional[Mesh]:
    return getattr(_state, "mesh", None)


def current_rules():
    return getattr(_state, "rules", DEFAULT_RULES)


@contextlib.contextmanager
def axis_rules(mesh: Optional[Mesh], rules=None):
    """Activate a mesh + logical->physical rules for model building / tracing."""
    prev = (current_mesh(), current_rules())
    _state.mesh = mesh
    _state.rules = tuple(rules) if rules is not None else DEFAULT_RULES
    try:
        yield
    finally:
        _state.mesh, _state.rules = prev


def logical_to_spec(axes: Sequence[Optional[str]], mesh: Optional[Mesh] = None,
                    rules=None) -> P:
    """Map logical axis names to a PartitionSpec valid on ``mesh``.

    Logical axes with no rule, a rule to None, or a rule naming a mesh axis that
    doesn't exist on this mesh become unsharded (None) — so the same model code
    runs on any mesh shape, incl. single device.
    """
    mesh = mesh if mesh is not None else current_mesh()
    rules = rules if rules is not None else current_rules()
    mesh_axes = set(mesh.axis_names) if mesh is not None else set()
    table = dict(rules)
    entries = []
    used = set()
    for ax in axes:
        phys = table.get(ax) if ax is not None else None
        if phys is None:
            entries.append(None)
            continue
        if isinstance(phys, str):
            phys = (phys,)
        keep = tuple(p for p in phys if p in mesh_axes and p not in used)
        used.update(keep)
        if not keep:
            entries.append(None)
        elif len(keep) == 1:
            entries.append(keep[0])
        else:
            entries.append(keep)
    return P(*entries)


def annotate(param, *axes: Optional[str]):
    """Attach logical axis names to a Tensor/Parameter (one per dim)."""
    param.logical_axes = tuple(axes)
    return param


def param_sharding(param, mesh: Optional[Mesh] = None) -> Optional[NamedSharding]:
    mesh = mesh if mesh is not None else current_mesh()
    if mesh is None:
        return None
    axes = getattr(param, "logical_axes", None)
    ndim = param._data.ndim if hasattr(param, "_data") else param.ndim
    if axes is None:
        axes = (None,) * ndim
    return NamedSharding(mesh, logical_to_spec(axes, mesh))


def shard_params(layer, mesh: Optional[Mesh] = None):
    """device_put every parameter/buffer of a Layer per its logical axes."""
    mesh = mesh if mesh is not None else current_mesh()
    if mesh is None:
        return layer
    for _, p in layer.named_parameters():
        s = param_sharding(p, mesh)
        if s is not None and not isinstance(p._data, jax.core.Tracer):
            p._data = jax.device_put(p._data, s)
    for _, b in layer.named_buffers():
        s = param_sharding(b, mesh)
        if s is not None and not isinstance(b._data, jax.core.Tracer):
            b._data = jax.device_put(b._data, s)
    return layer


def constrain(x, *axes: Optional[str]):
    """with_sharding_constraint by logical axes; no-op when no mesh is active.

    Accepts a jax.Array or framework Tensor; returns the same kind.
    """
    mesh = current_mesh()
    if mesh is None:
        return x
    from ...core.tensor import Tensor

    spec = logical_to_spec(axes, mesh)
    if isinstance(x, Tensor):
        x._data = jax.lax.with_sharding_constraint(x._data, NamedSharding(mesh, spec))
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def make_mesh(shape_by_axis, devices=None) -> Mesh:
    """Build a Mesh from {"dp": 2, "fsdp": 2, "tp": 2, ...} (axes with size 1 kept).

    Device order follows jax.devices(); ICI-friendly: innermost axes ("tp") get
    neighboring devices so tensor-parallel collectives ride the fastest links.
    """
    import numpy as np

    devices = devices if devices is not None else jax.devices()
    names = [n for n, s in shape_by_axis.items()]
    sizes = [int(s) for _, s in shape_by_axis.items()]
    n = int(np.prod(sizes))
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(sizes)
    return Mesh(arr, tuple(names))
