"""Distributed training Engine — one jitted SPMD train step over a device mesh.

Parity anchor: the reference's auto-parallel Engine
(/root/reference/python/paddle/distributed/auto_parallel/static/engine.py:98 —
completion → partition → reshard-insertion passes) and the Fleet hybrid optimizer
(fleet/meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py:258).

TPU-native collapse: there are no passes. The whole train step
(forward → loss → backward → global-norm clip → AdamW) is ONE jitted function;
parameters, grads, and optimizer state carry NamedShardings derived from logical
axis rules, and GSPMD inserts every collective:
  - dp/fsdp grad reduction  ≙ reference EagerReducer allreduce (collective/reducer.cc)
  - fsdp param gather       ≙ ZeRO-3 on-demand allgather (group_sharded_stage3.py:85)
  - fsdp opt-state sharding ≙ ZeRO-1 (dygraph_sharding_optimizer.py:48)
  - tp activations          ≙ mp_layers.py column/row parallel collectives
Buffers are donated so params/opt-state update in-place in HBM.
"""

from __future__ import annotations

import math
from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from ...framework import numeric_guard
from ...nn.layer.layers import Layer
from .logical_sharding import (
    DEFAULT_RULES,
    axis_rules,
    current_mesh,
    logical_to_spec,
    param_sharding,
    shard_params,
)


def _batch_sharding(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    # [batch, seq] inputs: batch over dp+fsdp, seq over sep
    axes = ["batch", "seq"] + [None] * (ndim - 2)
    return NamedSharding(mesh, logical_to_spec(axes[:ndim], mesh))


_DEFAULT_CLIP = object()  # sentinel: "caller did not choose" vs explicit value


class _ParamProxy:
    """Shape/dtype/name carrier handed to ``Optimizer._update`` inside the
    jitted train step. The Engine functionalizes params into bare arrays
    (stacked pipeline params never have a live Tensor at all), but the
    optimizer state machinery keys accumulators off a param object — this is
    that object."""

    __slots__ = ("shape", "dtype", "name", "optimize_attr")

    def __init__(self, shape, dtype, name):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name
        self.optimize_attr = {"learning_rate": 1.0}


class Engine:
    """Jitted SPMD trainer for a Layer with a ``loss_fn(input_ids, labels)``.

    Usage::

        mesh = make_mesh({"dp": 1, "fsdp": 2, "sep": 1, "tp": 2})
        with axis_rules(mesh):
            model = LlamaForCausalLM(cfg)       # params created sharded
        eng = Engine(model, mesh, lr=3e-4)
        loss = eng.step(input_ids, labels)       # one fused XLA program
    """

    def __init__(
        self,
        model: Layer,
        mesh: Optional[Mesh] = None,
        *,
        lr: Union[float, Callable[[jax.Array], jax.Array]] = 1e-3,
        beta1: float = 0.9,
        beta2: float = 0.95,
        epsilon: float = 1e-8,
        weight_decay: float = 0.1,
        apply_decay_param_fun: Optional[Callable[[str], bool]] = None,
        clip_norm: Optional[float] = _DEFAULT_CLIP,
        rules=None,
        loss_fn: Optional[Callable] = None,
        donate: bool = True,
        n_micro: Optional[int] = None,
        pp_remat: Optional[bool] = None,
        pp_interleave: int = 1,
        pp_schedule: str = "auto",
        pp_remat_policy="auto",
        optimizer=None,
        abstract_state: bool = False,
        guard=None,
    ):
        self.model = model
        self.mesh = mesh if mesh is not None else current_mesh()
        self.rules = tuple(rules) if rules is not None else DEFAULT_RULES
        self.lr = lr
        self.beta1, self.beta2 = beta1, beta2
        self.epsilon = epsilon
        self.weight_decay = weight_decay
        self.clip_norm = 1.0 if clip_norm is _DEFAULT_CLIP else clip_norm
        self._loss_fn = loss_fn
        self._donate = donate

        # --- pipeline parallelism: peel block params off for pp-stacking ---
        pp_size = self.mesh.shape.get("pp", 1) if self.mesh is not None else 1
        self._pp = pp_size > 1 and hasattr(model, "pipeline_blocks")
        self._blocks = model.pipeline_blocks() if self._pp else []
        self._pp_interleave = pp_interleave if self._pp else 1
        # "auto" = GPipe / interleaved-VPP; "zb" = zero-bubble W/B split
        # (reference ZBH1, pipeline_scheduler_pass/__init__.py:22)
        self._pp_schedule = pp_schedule
        if self._pp and len(self._blocks) % (pp_size * self._pp_interleave) != 0:
            raise ValueError(
                f"num blocks {len(self._blocks)} not divisible by "
                f"pp*interleave={pp_size}*{self._pp_interleave}")
        self._n_micro = n_micro if n_micro is not None else max(pp_size, 1)
        if self._pp and self._pp_interleave > 1 and self._n_micro % pp_size != 0:
            raise ValueError(
                f"VPP needs n_micro % pp == 0, got {self._n_micro} % {pp_size}")
        self._pp_remat = (pp_remat if pp_remat is not None
                          else bool(getattr(getattr(model, "config", None), "recompute", False)))
        # the model's remat policy (e.g. save flash out+lse) applies to the
        # pipelined block remat too — same knob, both paths. Models expose it
        # via a ``remat_policy()`` hook (no model-specific imports here).
        # EXCEPT on the zb schedule: zb's selective regime stacks the tick's
        # param slice per microbatch (see zb_schedule's memory-regime notes),
        # so zb + pp_remat keeps the round-4 boundary-storage default; pass
        # pp_remat_policy="model" (or a policy) to opt into selective zb.
        pol_fn = getattr(model, "remat_policy", None)
        model_policy = pol_fn() if callable(pol_fn) else None
        if pp_remat_policy == "auto":
            self._pp_remat_policy = (None if pp_schedule == "zb"
                                     else model_policy)
        elif pp_remat_policy == "model":
            self._pp_remat_policy = model_policy
        else:
            self._pp_remat_policy = pp_remat_policy
        block_param_ids = {id(t) for b in self._blocks for _, t in b.named_parameters()}

        # --- functionalize: ordered trainable params (non-block "rest" first) ---
        self._param_tensors = [p for _, p in model.named_parameters()
                               if not p.stop_gradient and id(p) not in block_param_ids]
        self._param_names = [n for n, p in model.named_parameters()
                             if not p.stop_gradient and id(p) not in block_param_ids]
        # weight-decay mask: like the reference recipes (apply_decay_param_fun),
        # norm gains and biases (ndim <= 1) are excluded by default
        if apply_decay_param_fun is not None:
            self._decay_mask = [bool(apply_decay_param_fun(n)) for n in self._param_names]
        else:
            self._decay_mask = [p._data.ndim >= 2 for p in self._param_tensors]
        if self.mesh is not None:
            with axis_rules(self.mesh, self.rules):
                shard_params(model, self.mesh)
        self.params = [p._data for p in self._param_tensors]

        # pipeline: stack block params [n_layers, ...] sharded P("pp", <block axes>)
        self._n_rest = len(self.params)
        self._block_shardings = []
        if self._pp:
            from .pipeline import stack_block_params

            if self._loss_fn is not None:
                raise ValueError(
                    "custom loss_fn is not supported with pipeline parallelism "
                    "(pp > 1) — the pp path runs model.pipeline_loss")
            with axis_rules(self.mesh, self.rules):
                stacked, bshard, bnames, bdecay, self._pp_order = \
                    stack_block_params(self._blocks, self.mesh,
                                       interleave=self._pp_interleave)
            self.params = self.params + stacked
            if apply_decay_param_fun is not None:
                # per-layer decay decisions collapse to the block-level name
                # (all layers of a stack share one stacked param)
                bdecay = [bool(apply_decay_param_fun(n)) for n in bnames]
            self._param_names = self._param_names + [f"blocks.{n}" for n in bnames]
            self._decay_mask = self._decay_mask + bdecay
            self._block_shardings = bshard
            self._block_fn = self.model.pipeline_block_fn(self._blocks[0])
            self._pp_with_aux = bool(getattr(self.model, "pipeline_with_aux", False))
            # free the unstacked per-layer originals — otherwise the Layer
            # tensors pin a second full copy of the decoder weights in HBM.
            # sync_model() restores them by slicing the stacked arrays.
            for b in self._blocks:
                for _, t in b.named_parameters():
                    t._data = None

        # optimizer state, sharded like the params (ZeRO: fsdp axis shards them)
        self._shardings = None
        if self.mesh is not None:
            with axis_rules(self.mesh, self.rules):
                self._shardings = [param_sharding(p, self.mesh) for p in self._param_tensors]
            self._shardings = self._shardings + self._block_shardings

        self._abstract_state = abstract_state
        if abstract_state and (optimizer is not None or self.mesh is None):
            raise ValueError(
                "abstract_state=True requires the built-in AdamW path and a "
                "mesh (it exists to AOT-lower the hybrid step without "
                "materializing fp32 m/v)")
        self._optimizer = optimizer
        self.m = self.v = None
        self.opt_state = None
        if optimizer is None:
            # built-in fused AdamW fast path
            if abstract_state and self.mesh is not None:
                # AOT-lowering mode: optimizer state as sharded
                # ShapeDtypeStructs — ``lower()`` needs shapes + shardings
                # only, so configs whose fp32 m/v exceed host RAM (7B+ on a
                # virtual mesh) can still trace/lower the full hybrid step.
                # step() is NOT runnable in this mode.
                zeros = lambda a, s: jax.ShapeDtypeStruct(
                    a.shape, jnp.float32, sharding=s)
                self.m = [zeros(a, s) for a, s in zip(self.params, self._shardings)]
                self.v = [zeros(a, s) for a, s in zip(self.params, self._shardings)]
            elif self.mesh is not None:
                zeros = lambda a, s: jax.device_put(jnp.zeros(a.shape, jnp.float32), s)
                self.m = [zeros(a, s) for a, s in zip(self.params, self._shardings)]
                self.v = [zeros(a, s) for a, s in zip(self.params, self._shardings)]
            else:
                self.m = [jnp.zeros(a.shape, jnp.float32) for a in self.params]
                self.v = [jnp.zeros(a.shape, jnp.float32) for a in self.params]
        else:
            # pluggable path: any paddle_tpu.optimizer.Optimizer runs inside the
            # jitted SPMD step via its pure _functional_update (reference parity:
            # HybridParallelOptimizer wraps any inner optimizer,
            # hybrid_parallel_optimizer.py:258)
            oc = getattr(optimizer, "_grad_clip", None)
            if oc is not None:
                # only global-norm clip is expressible in the SPMD step; other
                # clip classes must not be silently reinterpreted
                if type(oc).__name__ != "ClipGradByGlobalNorm":
                    raise ValueError(
                        f"Engine supports ClipGradByGlobalNorm only, got "
                        f"{type(oc).__name__}; pass clip_norm=... instead")
                if clip_norm is _DEFAULT_CLIP:
                    self.clip_norm = oc.clip_norm
            self._proxies = [_ParamProxy(a.shape, a.dtype, n)
                             for a, n in zip(self.params, self._param_names)]
            self.opt_state, self._opt_state_shardings = self._init_opt_state()
        self.step_count = jnp.zeros((), jnp.int32)
        self._jit_step = None
        self._jit_loss = None

        # --- numeric guard (framework/numeric_guard.py): checkify-style
        # health word computed inside the jitted step; the host reads ONE
        # aggregated int32 scalar per step (rides the loss's sync).
        self.guard = guard
        self.guard_state = None
        self.last_health = None     # int32 device scalar after each step
        self.lr_scale = 1.0         # LR re-warm multiplier (watchdog-driven)
        self._host_step = 0         # host mirror of step_count (fault detail)
        if guard is not None:
            if optimizer is not None:
                raise ValueError(
                    "numeric guard supports the built-in AdamW path only "
                    "(pass guard=None with a pluggable optimizer)")
            state = numeric_guard.guard_init_state()
            if self.mesh is not None:
                state = jax.device_put(state, NamedSharding(self.mesh, P()))
            self.guard_state = state

    # ---- pluggable-optimizer state ----
    def _init_opt_state(self):
        """Discover the optimizer's accumulator pytree and materialize it sharded.

        Two probes: (1) a concrete scalar-shaped run records each accumulator's
        INIT value (Adagrad's initial_accumulator_value, NAdam's mu_product=1 —
        eval_shape alone would lose these); (2) an eval_shape run on the real
        param shapes gives each accumulator's shape/dtype. Param-shaped
        accumulators inherit the param's NamedSharding (ZeRO via fsdp axis);
        scalar state is replicated."""
        opt = self._optimizer
        inits: dict = {}
        orig_acc = opt._acc

        def probing_acc(name, p, init=None, dtype=None):
            d = opt._accumulators.setdefault(name, {})
            fresh = id(p) not in d
            out = orig_acc(name, p, init=init, dtype=dtype)
            if fresh:
                arr = jnp.asarray(out)
                inits[name] = float(arr.reshape(-1)[0]) if arr.size else 0.0
            return out

        scalar_proxies = [_ParamProxy((), a.dtype, n)
                          for a, n in zip(self.params, self._param_names)]
        opt._acc = probing_acc
        try:
            opt._functional_update(
                [jnp.zeros((), jnp.float32) for _ in self.params],
                [jnp.zeros((), a.dtype) for a in self.params],
                scalar_proxies, {}, 1e-3, 1)
        finally:
            opt._acc = orig_acc

        def probe(grads, values):
            _, acc = opt._functional_update(grads, values, self._proxies, {}, 1e-3, 1)
            return acc

        g_avals = [jax.ShapeDtypeStruct(a.shape, jnp.float32) for a in self.params]
        v_avals = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in self.params]
        acc_struct = jax.eval_shape(probe, g_avals, v_avals)

        id2idx = {id(p): i for i, p in enumerate(self._proxies)}
        rep = NamedSharding(self.mesh, P()) if self.mesh is not None else None
        state, shardings = {}, {}
        for name, d in acc_struct.items():
            sub, ssub = {}, {}
            for pid, aval in d.items():
                i = id2idx[pid]
                fill = inits.get(name, 0.0)
                arr = (jnp.zeros(aval.shape, aval.dtype) if fill == 0.0
                       else jnp.full(aval.shape, fill, aval.dtype))
                if self.mesh is not None:
                    sh = (self._shardings[i]
                          if tuple(aval.shape) == tuple(self.params[i].shape) else rep)
                    arr = jax.device_put(arr, sh)
                    ssub[i] = sh
                sub[i] = arr
            state[name] = sub
            shardings[name] = ssub
        return state, (shardings if self.mesh is not None else None)

    def _clip_grads(self, grads):
        if self.clip_norm is None:
            return grads
        # global-norm clip across ALL params — the reference clips across
        # MP/PP groups too (hybrid_parallel_optimizer.py); here the grads are
        # global (GSPMD), so a plain global norm is already group-correct.
        gsq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads)
        gnorm = jnp.sqrt(gsq)
        scale = jnp.minimum(1.0, self.clip_norm / jnp.maximum(gnorm, 1e-6))
        return [g * scale.astype(g.dtype) for g in grads]

    def _current_lr(self) -> float:
        """Host-side scalar fed to the jitted step as an argument each call —
        LRScheduler objects advance on host (scheduler.step()), no retrace."""
        opt = self._optimizer
        try:
            return float(opt.get_lr())
        except Exception:
            lr = opt._learning_rate
            return float(lr() if callable(lr) else lr)

    # ---- pure functions ----
    def _pure_loss(self, param_arrays, input_ids, labels):
        from ...jit.api import _Swap
        from ...core import autograd_engine

        model = self.model
        if self._pp:
            from .pipeline import pipeline_call

            rest = param_arrays[: self._n_rest]
            stacked = param_arrays[self._n_rest:]

            def run_blocks(x, cos, sin):
                res = pipeline_call(
                    self._block_fn, stacked, x, cos, sin,
                    mesh=self.mesh, n_micro=self._n_micro,
                    remat=self._pp_remat, with_aux=self._pp_with_aux,
                    interleave=self._pp_interleave,
                    remat_policy=self._pp_remat_policy,
                    schedule=self._pp_schedule)
                if self._pp_with_aux:
                    # aux is summed per microbatch; average to match the
                    # whole-batch scale of the non-pp path
                    x_out, aux = res
                    return x_out, aux / float(self._n_micro)
                return res

            with autograd_engine.no_grad(), _Swap(self._param_tensors, rest), \
                    axis_rules(self.mesh, self.rules):
                out = model.pipeline_loss(input_ids, labels, run_blocks)
            return out._data if isinstance(out, Tensor) else out
        fn = self._loss_fn or (lambda ids, lb: model.loss_fn(ids, lb))
        with autograd_engine.no_grad(), _Swap(self._param_tensors, param_arrays), \
                axis_rules(self.mesh, self.rules):
            out = fn(input_ids, labels)
        return out._data if isinstance(out, Tensor) else out

    def _adamw(self, params, m, v, grads, step, lr_scale=None):
        b1, b2, eps, wd = self.beta1, self.beta2, self.epsilon, self.weight_decay
        lr = self.lr(step) if callable(self.lr) else self.lr
        if lr_scale is not None:
            lr = lr * lr_scale      # post-rollback re-warm (traced scalar arg)
        stepf = step.astype(jnp.float32)
        bc1 = 1.0 - b1 ** stepf
        bc2 = 1.0 - b2 ** stepf

        grads = self._clip_grads(grads)

        new_p, new_m, new_v = [], [], []
        for p, mm, vv, g, decay in zip(params, m, v, grads, self._decay_mask):
            gf = g.astype(jnp.float32)
            mm2 = b1 * mm + (1.0 - b1) * gf
            vv2 = b2 * vv + (1.0 - b2) * gf * gf
            update = (mm2 / bc1) / (jnp.sqrt(vv2 / bc2) + eps)
            pf = p.astype(jnp.float32)
            pf = pf - lr * (update + (wd * pf if decay else 0.0))
            new_p.append(pf.astype(p.dtype))
            new_m.append(mm2)
            new_v.append(vv2)
        return new_p, new_m, new_v

    def _build_step(self):
        def train_step(params, m, v, step, input_ids, labels):
            step = step + 1
            loss, grads = jax.value_and_grad(self._pure_loss)(params, input_ids, labels)
            new_p, new_m, new_v = self._adamw(params, m, v, grads, step)
            return new_p, new_m, new_v, step, loss

        kw = {}
        if self.mesh is not None:
            sh = self._shardings
            bsh = _batch_sharding(self.mesh)
            rep = NamedSharding(self.mesh, P())
            kw["in_shardings"] = (sh, sh, sh, rep, bsh, bsh)
            kw["out_shardings"] = (sh, sh, sh, rep, rep)
        if self._donate:
            kw["donate_argnums"] = (0, 1, 2, 3)
        return jax.jit(train_step, **kw)

    def _build_guard_step(self):
        """Guarded train step: same fused fwd/bwd/clip/AdamW program plus a
        checkify-style health word (one int32 scalar, no per-tensor host
        syncs) and an in-graph zero-apply — an anomalous step advances the
        step counter but leaves params and optimizer moments untouched.

        ``inject`` (faults.numeric_inject_code) and ``lr_scale`` (re-warm)
        arrive as traced scalars, so neither fault drills nor the warmup
        ramp ever retrace."""
        pol = self.guard
        skip_mask = pol.skip_mask
        ng = numeric_guard

        def train_step(params, m, v, step, gstate, input_ids, labels,
                       inject, lr_scale):
            step = step + 1

            def lossf(ps):
                l = self._pure_loss(ps, input_ids, labels)
                spike = jnp.where(inject == ng.INJECT_LOSS_SPIKE,
                                  ng.SPIKE_INJECT_FACTOR, 1.0)
                return (l.astype(jnp.float32) * spike).astype(l.dtype)

            loss, grads = jax.value_and_grad(lossf)(params)
            nan = jnp.where(inject == ng.INJECT_NAN_GRAD,
                            jnp.float32(jnp.nan), jnp.float32(0.0))
            grads = [g + nan.astype(g.dtype) for g in grads]
            word, new_state = ng.guard_step(
                loss, grads, gstate, spike_factor=pol.spike_factor,
                warmup_steps=pol.warmup_steps)
            new_p, new_m, new_v = self._adamw(params, m, v, grads, step,
                                              lr_scale)
            bad = (word & skip_mask) != 0

            def pick(news, olds):
                return [jnp.where(bad, o, n) for n, o in zip(news, olds)]

            return (pick(new_p, params), pick(new_m, m), pick(new_v, v),
                    step, new_state, loss, word)

        kw = {}
        if self.mesh is not None:
            sh = self._shardings
            bsh = _batch_sharding(self.mesh)
            rep = NamedSharding(self.mesh, P())
            kw["in_shardings"] = (sh, sh, sh, rep, rep, bsh, bsh, rep, rep)
            kw["out_shardings"] = (sh, sh, sh, rep, rep, rep, rep)
        if self._donate:
            kw["donate_argnums"] = (0, 1, 2, 3, 4)
        return jax.jit(train_step, **kw)

    def _build_opt_step(self):
        """Train step around a pluggable ``paddle_tpu.optimizer.Optimizer``:
        its per-tensor ``_update`` rules trace into the same single jitted SPMD
        program as the built-in AdamW path (lr arrives as an argument so host-
        side LR schedules never retrace)."""
        opt = self._optimizer
        id2idx = {id(p): i for i, p in enumerate(self._proxies)}

        def train_step(params, opt_state, step, lr, input_ids, labels):
            step = step + 1
            loss, grads = jax.value_and_grad(self._pure_loss)(params, input_ids, labels)
            grads = self._clip_grads(grads)
            grads = [g.astype(jnp.float32) for g in grads]
            acc = {name: {id(self._proxies[i]): a for i, a in d.items()}
                   for name, d in opt_state.items()}
            new_p, new_acc = opt._functional_update(
                grads, params, self._proxies, acc, lr, step.astype(jnp.float32))
            new_state = {name: {id2idx[pid]: a for pid, a in d.items()}
                         for name, d in new_acc.items()}
            return new_p, new_state, step, loss

        kw = {}
        if self.mesh is not None:
            sh = self._shardings
            osh = self._opt_state_shardings
            bsh = _batch_sharding(self.mesh)
            rep = NamedSharding(self.mesh, P())
            kw["in_shardings"] = (sh, osh, rep, rep, bsh, bsh)
            kw["out_shardings"] = (sh, osh, rep, rep)
        if self._donate:
            kw["donate_argnums"] = (0, 1, 2)
        return jax.jit(train_step, **kw)

    # ---- public API ----
    def shard_batch(self, *arrays):
        """device_put host batches onto the mesh (dp×fsdp batch, sep seq)."""
        if self.mesh is None:
            return tuple(jnp.asarray(a) for a in arrays) if len(arrays) > 1 else jnp.asarray(arrays[0])
        out = tuple(jax.device_put(jnp.asarray(a), _batch_sharding(self.mesh, jnp.ndim(a)))
                    for a in arrays)
        return out if len(out) > 1 else out[0]

    def step(self, input_ids, labels):
        """Run one fused train step; returns the (device) scalar loss."""
        if self._abstract_state:
            raise RuntimeError(
                "Engine was built with abstract_state=True (AOT-lowering "
                "mode): optimizer state is ShapeDtypeStructs, step() cannot "
                "execute — use _build_step().lower(...) instead")
        ids = input_ids._data if isinstance(input_ids, Tensor) else jnp.asarray(input_ids)
        lbl = labels._data if isinstance(labels, Tensor) else jnp.asarray(labels)
        if self.guard is not None:
            if self._jit_step is None:
                self._jit_step = self._build_guard_step()
            self._host_step += 1
            from ..resilience.faults import numeric_inject_code

            inject = numeric_inject_code(str(self._host_step))
            (self.params, self.m, self.v, self.step_count, self.guard_state,
             loss, health) = self._jit_step(
                self.params, self.m, self.v, self.step_count,
                self.guard_state, ids, lbl,
                jnp.asarray(inject, jnp.int32),
                jnp.asarray(self.lr_scale, jnp.float32))
            self.last_health = health
            return loss
        if self._optimizer is not None:
            if self._jit_step is None:
                self._jit_step = self._build_opt_step()
            lr = jnp.asarray(self._current_lr(), jnp.float32)
            self.params, self.opt_state, self.step_count, loss = self._jit_step(
                self.params, self.opt_state, self.step_count, lr, ids, lbl)
            return loss
        if self._jit_step is None:
            self._jit_step = self._build_step()
        self.params, self.m, self.v, self.step_count, loss = self._jit_step(
            self.params, self.m, self.v, self.step_count, ids, lbl)
        return loss

    def eval_loss(self, input_ids, labels):
        if self._jit_loss is None:
            kw = {}
            if self.mesh is not None:
                bsh = _batch_sharding(self.mesh)
                kw["in_shardings"] = (self._shardings, bsh, bsh)
            self._jit_loss = jax.jit(self._pure_loss, **kw)
        ids = input_ids._data if isinstance(input_ids, Tensor) else jnp.asarray(input_ids)
        lbl = labels._data if isinstance(labels, Tensor) else jnp.asarray(labels)
        return self._jit_loss(self.params, ids, lbl)

    def sync_model(self):
        """Write the (updated) param arrays back into the Layer tensors.

        Copies, not aliases: the step() jit donates its param buffers, so handing
        out the live arrays would leave the Layer pointing at deleted memory
        after the next step (donation is a no-op on CPU but real on TPU).
        """
        for t, a in zip(self._param_tensors, self.params[: self._n_rest]):
            t._data = jnp.copy(a)
        if self._pp:
            per_block = [[t for _, t in b.named_parameters()] for b in self._blocks]
            # stacked row r holds layer self._pp_order[r] (VPP reordering)
            for i, st in enumerate(self.params[self._n_rest:]):
                for r, li in enumerate(self._pp_order):
                    per_block[li][i]._data = jnp.copy(st[r])
        return self.model

    def state_dict(self):
        self.sync_model()
        out = {"model": self.model.state_dict(), "step": jnp.copy(self.step_count)}
        if self._optimizer is not None:
            out["opt"] = {
                name: {self._param_names[i]: jnp.copy(a) for i, a in d.items()}
                for name, d in self.opt_state.items()}
        else:
            out["m"] = {n: jnp.copy(a) for n, a in zip(self._param_names, self.m)}
            out["v"] = {n: jnp.copy(a) for n, a in zip(self._param_names, self.v)}
        return out

    def set_state_dict(self, state_dict):
        """Resume-in-place from a ``state_dict()`` snapshot (params,
        optimizer accumulators, step count) — the counterpart
        ``ResilientTrainer`` calls after a checkpoint ``load_state_dict``
        reshards the snapshot onto THIS engine's mesh. Arrays are
        device_put to the engine's shardings, so a snapshot from a
        different mesh resumes bit-for-bit on the new one."""
        if self._pp:
            raise NotImplementedError(
                "set_state_dict with pipeline-stacked params is not "
                "supported yet — rebuild the Engine and load via "
                "model.set_state_dict")
        self.model.set_state_dict(state_dict["model"])
        rep = (NamedSharding(self.mesh, P()) if self.mesh is not None else None)

        def put(a, sh):
            arr = a._data if isinstance(a, Tensor) else jnp.asarray(a)
            return jax.device_put(arr, sh) if sh is not None else jnp.asarray(arr)

        shardings = self._shardings or [None] * len(self._param_tensors)
        for t, sh in zip(self._param_tensors, shardings):
            t._data = put(t, sh)
        self.params = [t._data for t in self._param_tensors]
        if self._optimizer is not None:
            opt = state_dict["opt"]
            name2idx = {n: i for i, n in enumerate(self._param_names)}
            self.opt_state = {
                acc: {name2idx[n]: put(a, self._opt_state_shardings[acc]
                                       [name2idx[n]] if self.mesh is not None
                                       else None)
                      for n, a in d.items()}
                for acc, d in opt.items()}
        else:
            ms, vs = state_dict["m"], state_dict["v"]
            missing = [n for n in self._param_names if n not in ms or n not in vs]
            if missing:
                raise KeyError(f"optimizer state missing for params {missing}")
            self.m = [put(ms[n], sh) for n, sh in zip(self._param_names, shardings)]
            self.v = [put(vs[n], sh) for n, sh in zip(self._param_names, shardings)]
        step = state_dict["step"]
        step = step._data if isinstance(step, Tensor) else jnp.asarray(step)
        self.step_count = (jax.device_put(step.astype(jnp.int32), rep)
                           if rep is not None else step.astype(jnp.int32))
        return self


ShardedTrainer = Engine
