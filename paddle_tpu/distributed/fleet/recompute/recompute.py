"""Activation recompute (reference: fleet/recompute/recompute.py:455).

TPU-native: ``jax.checkpoint`` (remat) IS activation checkpointing — the backward
pass recomputes the segment instead of storing activations, trading FLOPs for HBM.
In eager-tape mode we wrap the segment so the recorded vjp closure holds only the
segment *inputs* (not its internals): jax.vjp over jax.checkpoint(fn).
"""

from __future__ import annotations

from typing import Callable

import jax

from ....core.op_registry import apply_fn
from ....core.tensor import Tensor


def recompute(function: Callable, *args, **kwargs):
    """Run function now; recompute it during backward (reference recompute():455)."""
    use_reentrant = kwargs.pop("use_reentrant", True)
    preserve_rng_state = kwargs.pop("preserve_rng_state", True)

    static_kwargs = {k: v for k, v in kwargs.items() if not isinstance(v, Tensor)}

    fn = function.forward if hasattr(function, "forward") and not callable(function) else function

    def pure(*arrs):
        wrapped = [Tensor(a) for a in arrs]
        from ....core import autograd_engine

        with autograd_engine.no_grad():
            out = fn(*wrapped, **static_kwargs)
        if isinstance(out, (tuple, list)):
            return tuple(o._data if isinstance(o, Tensor) else o for o in out)
        return out._data if isinstance(out, Tensor) else out

    ckpt = jax.checkpoint(pure)
    return apply_fn("recompute", ckpt, *args)


def recompute_sequential(ctx, functions, *args, **kwargs):
    """Reference: recompute_sequential — chunk a Sequential into recomputed segments."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    layers = list(functions)
    n = len(layers)
    per = max(n // segments, 1)
    out = args[0] if len(args) == 1 else args
    for i in range(0, n, per):
        seg = layers[i:i + per]

        def seg_fn(x, _seg=seg):
            for l in _seg:
                x = l(x)
            return x

        out = recompute(seg_fn, out)
    return out
