"""Fleet elastic training (reference: python/paddle/distributed/fleet/elastic).

TCPStore-backed instead of etcd (zero extra deps): nodes heartbeat into the
store with TTL semantics; the manager watches peers and reports scale events.
"""

from .manager import ElasticManager, ElasticStatus, enable_elastic, launch_elastic  # noqa: F401
