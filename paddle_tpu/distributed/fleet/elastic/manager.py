"""ElasticManager — node liveness + scale events over the TCPStore.

Reference: python/paddle/distributed/fleet/elastic/manager.py:125 — ranks
register in etcd with TTL leases (manager.py:248-293), watch callbacks detect
node join/loss, and the job relaunches between min/max nranks (fault tolerance
= restart from checkpoint). TPU-native: the lease is a heartbeat key
``elastic/{job}/beat/{node_id}`` refreshed by a daemon thread; peers whose
beat goes stale past ``ttl`` are dead. No etcd — the native TCPStore daemon
is the registry.

Clock discipline: the heartbeat is a **server-side counter** (``store.add``)
— the store daemon is the single ordering authority — and staleness is
measured by each observer's local ``time.monotonic()`` since the peer's
counter last advanced. Wall-clock (``time.time``) never crosses hosts, so
NTP skew can neither kill a live peer nor keep a dead one alive
(tests/test_resilience.py pins the skew regression).

Fault site ``elastic.heartbeat`` (docs/RESILIENCE.md): a ``kill`` fault
raised before a beat terminates the heartbeat thread — the injected
equivalent of node death, used by tools/fault_drill.py to exercise the
save/reshard/resume path.
"""

from __future__ import annotations

import threading
import time
from enum import Enum
from typing import Callable, Dict, List, Optional, Sequence

from ...resilience import faults as _faults


class ElasticStatus(Enum):
    COMPLETED = "completed"
    RESTART = "restart"
    HOLD = "hold"
    EXIT = "exit"
    ERROR = "error"


def _decode_count(raw: bytes) -> Optional[int]:
    """Beat counters arrive as the store's 8-byte little-endian int (from
    ``add``); tolerate the legacy ``repr(time.time())`` float-string beats
    (a mixed-version job mid-rolling-restart) by folding them into the
    staleness counter — any change still reads as an advance."""
    import struct

    if len(raw) == 8:
        return struct.unpack("<q", raw)[0]
    try:
        return int(float(raw.decode()))
    except (ValueError, UnicodeDecodeError):
        return None


class ElasticManager:
    def __init__(self, store, job_id: str, node_id: str,
                 expected: Sequence[str], heartbeat_interval: float = 3.0,
                 ttl: float = 9.0, clock: Callable[[], float] = time.monotonic):
        self.store = store
        self.job_id = job_id
        self.node_id = node_id
        self.expected = list(expected)
        self.interval = heartbeat_interval
        self.ttl = ttl
        self._clock = clock
        # node_id -> [last counter seen, local monotonic time it advanced]
        self._seen: Dict[str, list] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lease -------------------------------------------------------------
    def _beat_key(self, node_id: str) -> str:
        return f"elastic/{self.job_id}/beat/{node_id}"

    def _beat(self) -> None:
        _faults.maybe_inject("elastic.heartbeat", self.node_id)
        # monotone server-side counter: the store daemon is the clock
        # authority, never this host's wall clock. Over-count is harmless
        # for a staleness counter, so ambiguous transport outcomes retry.
        try:
            self.store.add(self._beat_key(self.node_id), 1,
                           on_ambiguous="retry")
        except TypeError:   # duck-typed store without the kwarg
            self.store.add(self._beat_key(self.node_id), 1)

    def start(self) -> None:
        if self.store is None:
            return
        self._beat()
        self._prime()

        def loop():
            while not self._stop.wait(self.interval):
                try:
                    self._beat()
                except _faults.FaultInjected:
                    return      # injected node death (fault drill)
                except Exception:
                    # transient store failure: the next interval IS the
                    # retry — one missed beat must not silently kill a
                    # healthy node's lease (peers allow ttl >> interval)
                    continue

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def _prime(self) -> None:
        """Record every expected peer's current counter NOW, so staleness
        for a peer that never beats again is measured from manager start —
        a fresh observer grants a dead-but-persisted beat key at most one
        ``ttl`` of grace, instead of ttl from whenever it first looks."""
        now = self._clock()
        for nid in self.expected:
            raw = self.store.get(self._beat_key(nid), wait=False)
            cnt = _decode_count(raw) if raw is not None else None
            if cnt is not None:
                self._seen.setdefault(nid, [cnt, now])

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    # -- watch -------------------------------------------------------------
    def alive_peers(self) -> List[str]:
        """Expected peers whose beat counter advanced within ``ttl`` of this
        observer's monotonic clock. A peer never seen to beat is dead."""
        if self.store is None:
            return [self.node_id]
        now = self._clock()
        alive = []
        for nid in self.expected:
            raw = self.store.get(self._beat_key(nid), wait=False)
            if raw is None:
                continue
            cnt = _decode_count(raw)
            if cnt is None:
                continue
            rec = self._seen.get(nid)
            if rec is None or cnt != rec[0]:
                self._seen[nid] = [cnt, now]
                alive.append(nid)
            elif now - rec[1] <= self.ttl:
                alive.append(nid)
        return alive

    def peers_changed(self) -> bool:
        """True when a registered PEER died (scale-in signal). This node's
        own beat lag never counts — a local store blip delaying our own
        heartbeat is not a peer loss, and treating it as one would burn an
        elastic restart on a healthy job. Scale-out is noticed at the next
        rendezvous generation, not here."""
        if self.store is None:
            return False
        alive = set(self.alive_peers())
        alive.add(self.node_id)
        return len(alive & set(self.expected)) < len(self.expected)

    def reset_expected(self, nodes: Sequence[str]) -> None:
        """Re-arm the watch for a new generation (post-reshard): only the
        surviving nodes are expected from now on."""
        self.expected = list(nodes)
        self._seen = {n: v for n, v in self._seen.items() if n in self.expected}


def enable_elastic(args=None, distribute_mode=None) -> bool:
    """Reference manager.py: elastic is on when a min:max node range is given."""
    import os

    rng = os.environ.get("PADDLE_ELASTIC_NNODES", "")
    return ":" in rng


def launch_elastic(args, distribute_mode=None):
    """Entry used by fleet tooling; delegates to the elastic controller."""
    from ...launch.controllers import CollectiveElasticController, Context, LaunchArgs

    if not isinstance(args, LaunchArgs):
        raise TypeError("launch_elastic expects LaunchArgs")
    return CollectiveElasticController(Context(args)).run()
