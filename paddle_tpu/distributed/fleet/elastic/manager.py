"""ElasticManager — node liveness + scale events over the TCPStore.

Reference: python/paddle/distributed/fleet/elastic/manager.py:125 — ranks
register in etcd with TTL leases (manager.py:248-293), watch callbacks detect
node join/loss, and the job relaunches between min/max nranks (fault tolerance
= restart from checkpoint). TPU-native: the lease is a heartbeat key
``elastic/{job}/beat/{node_id}`` holding a wall-clock stamp refreshed by a
daemon thread; peers whose stamp goes stale past ``ttl`` are dead. No etcd —
the native TCPStore daemon is the registry.
"""

from __future__ import annotations

import threading
import time
from enum import Enum
from typing import List, Optional, Sequence


class ElasticStatus(Enum):
    COMPLETED = "completed"
    RESTART = "restart"
    HOLD = "hold"
    EXIT = "exit"
    ERROR = "error"


class ElasticManager:
    def __init__(self, store, job_id: str, node_id: str,
                 expected: Sequence[str], heartbeat_interval: float = 3.0,
                 ttl: float = 9.0):
        self.store = store
        self.job_id = job_id
        self.node_id = node_id
        self.expected = list(expected)
        self.interval = heartbeat_interval
        self.ttl = ttl
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lease -------------------------------------------------------------
    def _beat_key(self, node_id: str) -> str:
        return f"elastic/{self.job_id}/beat/{node_id}"

    def _beat(self) -> None:
        self.store.set(self._beat_key(self.node_id), repr(time.time()).encode())

    def start(self) -> None:
        if self.store is None:
            return
        self._beat()

        def loop():
            while not self._stop.wait(self.interval):
                try:
                    self._beat()
                except Exception:
                    return  # store gone — controller is shutting down

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    # -- watch -------------------------------------------------------------
    def alive_peers(self) -> List[str]:
        if self.store is None:
            return [self.node_id]
        now = time.time()
        alive = []
        for nid in self.expected:
            raw = self.store.get(self._beat_key(nid), wait=False)
            if raw is None:
                continue
            try:
                stamp = float(raw.decode())
            except ValueError:
                continue
            if now - stamp <= self.ttl:
                alive.append(nid)
        return alive

    def peers_changed(self) -> bool:
        """True when a registered peer died (scale-in signal). Scale-out is
        noticed at the next rendezvous generation, not here."""
        if self.store is None:
            return False
        return len(self.alive_peers()) < len(self.expected)


def enable_elastic(args=None, distribute_mode=None) -> bool:
    """Reference manager.py: elastic is on when a min:max node range is given."""
    import os

    rng = os.environ.get("PADDLE_ELASTIC_NNODES", "")
    return ":" in rng


def launch_elastic(args, distribute_mode=None):
    """Entry used by fleet tooling; delegates to the elastic controller."""
    from ...launch.controllers import CollectiveElasticController, Context, LaunchArgs

    if not isinstance(args, LaunchArgs):
        raise TypeError("launch_elastic expects LaunchArgs")
    return CollectiveElasticController(Context(args)).run()
