"""HybridParallelOptimizer (reference: hybrid_parallel_optimizer.py:258).

The reference's job: clip grads with norms reduced across mp/pp groups, fuse DP
allreduces, then step. On TPU the global grad-norm over sharded grads is computed on
global arrays (XLA reduces across shards), so the wrapper reduces to: clip -> inner
step -> (sharding) keep opt state sharded.
"""

from __future__ import annotations


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        return self._inner_opt.minimize(loss)

    def clear_grad(self, set_to_zero=True):
        self._inner_opt.clear_grad()

    clear_gradients = clear_grad
