"""ZeRO-1/2 optimizer wrapper (reference: dygraph_sharding_optimizer.py:48, V2 :575).

TPU-native: "shard optimizer states across the sharding axis" = place every
accumulator with a NamedSharding that shards dim 0 over 'sharding'. The reference's
param-bucketing, broadcast-after-step, and reduce-scatter choreography are all
GSPMD's job here; XLA keeps the update math local to each shard and re-gathers
params where consumers need them.
"""

from __future__ import annotations

import jax


class DygraphShardingOptimizer:
    def __init__(self, optimizer, hcg=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        if hcg is not None and hcg.get_sharding_parallel_world_size() > 1:
            self._install_sharded_accumulators()

    def _install_sharded_accumulators(self):
        from ....sharding.group_sharded import install_sharded_accumulators

        install_sharded_accumulators(self._inner_opt, self._hcg.mesh, "sharding")

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, set_to_zero=True):
        self._inner_opt.clear_grad()

    clear_gradients = clear_grad


DygraphShardingOptimizerV2 = DygraphShardingOptimizer
