from .dygraph_optimizer.dygraph_sharding_optimizer import DygraphShardingOptimizer  # noqa: F401
from .dygraph_optimizer.hybrid_parallel_optimizer import HybridParallelOptimizer  # noqa: F401
