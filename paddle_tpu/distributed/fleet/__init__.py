"""paddle_tpu.distributed.fleet (reference: python/paddle/distributed/fleet)."""

from .base.distributed_strategy import DistributedStrategy  # noqa: F401
from .base.topology import CommunicateTopology, HybridCommunicateGroup, ParallelMode  # noqa: F401
from .fleet import (  # noqa: F401
    distributed_model,
    distributed_optimizer,
    get_hybrid_communicate_group,
    init,
)
from .recompute import recompute, recompute_sequential  # noqa: F401
