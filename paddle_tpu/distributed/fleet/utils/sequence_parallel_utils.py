"""Megatron-style sequence parallelism utilities.

Parity anchor: /root/reference/python/paddle/distributed/fleet/utils/
sequence_parallel_utils.py — ScatterOp:85 / GatherOp:97 / AllGatherOp /
ReduceScatterOp, ColumnSequenceParallelLinear:427, RowSequenceParallelLinear,
mark_as_sequence_parallel_parameter, register_sequence_parallel_allreduce_hooks:192.

TPU-native: activations annotated with a sequence-dim sharding over the mp
mesh axis; GSPMD materializes the scatter/gather/all-gather/reduce-scatter
that the reference codes by hand, and the XLA scheduler overlaps them with
matmuls (the job of the reference's SPInnerOverlapLinear). The explicit Op
classes remain as thin sharding-constraint primitives so reference training
code ports verbatim.
"""

from __future__ import annotations

from jax.sharding import PartitionSpec as P

from ....core.tensor import Tensor
from ....nn import functional as F
from ....nn import initializer as I
from ....nn.layer.layers import Layer
from ..meta_parallel.parallel_layers.mp_layers import _constrain, _mp_info, _place

__all__ = [
    "ScatterOp", "GatherOp", "AllGatherOp", "ReduceScatterOp",
    "mark_as_sequence_parallel_parameter",
    "register_sequence_parallel_allreduce_hooks",
    "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
]


def _seq_constrain(x, shard: bool):
    """Constrain activation [b, s, h] to sequence-sharded over the 'mp' axis
    (the fixed axis name of HybridCommunicateGroup's mesh) or replicated."""
    hcg, _ = _mp_info()
    arr = x._data if isinstance(x, Tensor) else x
    if hcg is None:
        return x if isinstance(x, Tensor) else Tensor(arr)
    spec = P(None, "mp", None) if shard else P(None, None, None)
    return Tensor(_constrain(arr, hcg.mesh, spec))


class ScatterOp:
    """Split activations along seq dim across the mp group (reference :85).
    Forward scatter == backward gather; GSPMD derives both from the spec."""

    @staticmethod
    def apply(x):
        return _seq_constrain(x, shard=True)


class GatherOp:
    """Gather seq-sharded activations back to full sequence (reference :97)."""

    @staticmethod
    def apply(x):
        return _seq_constrain(x, shard=False)


class AllGatherOp(GatherOp):
    """Alias semantics of GatherOp at the XLA level (all-gather over mp)."""


class ReduceScatterOp:
    """Sum partial activations and scatter along seq (reference :138).
    Under GSPMD the reduce comes from the producing matmul's partial sharding;
    the scatter is the seq-sharded constraint."""

    @staticmethod
    def apply(x):
        return _seq_constrain(x, shard=True)


def mark_as_sequence_parallel_parameter(parameter):
    """Tag params whose grads need mp-group allreduce in the reference
    (LayerNorm scales inside SP regions). Grads are globally correct under
    GSPMD already; the tag is kept for porting compatibility."""
    parameter.sequence_parallel = True
    return parameter


def register_sequence_parallel_allreduce_hooks(model, fuse_sequence_parallel_allreduce=False):
    """Reference registers fused grad-allreduce hooks for tagged params
    (:192). GSPMD's partitioner already reduces those grads — nothing to
    register; retained for API parity."""
    return model


class ColumnSequenceParallelLinear(Layer):
    """Column-parallel linear with sequence-parallel input: the input arrives
    seq-sharded, is all-gathered for the matmul, and the output is
    column-sharded (reference :427). All collectives come from the sharding
    specs."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=False, name=None):
        super().__init__()
        hcg, mp = _mp_info()
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.bias = (self.create_parameter(
            [out_features], default_initializer=I.Constant(0.0), is_bias=True)
            if has_bias else None)
        if hcg is not None:
            _place(self.weight, hcg.mesh, P(None, "mp"))
            if self.bias is not None:
                _place(self.bias, hcg.mesh, P("mp"))

    def forward(self, x):
        x = GatherOp.apply(x)  # seq-sharded -> full sequence for the matmul
        out = F.linear(x, self.weight, self.bias)
        hcg, mp = _mp_info()
        if hcg is not None and not self.gather_output:
            out = Tensor(_constrain(out._data, hcg.mesh, P(None, None, "mp")))
        return out


class RowSequenceParallelLinear(Layer):
    """Row-parallel linear whose output reduce-scatters along seq
    (reference RowSequenceParallelLinear)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True, name=None):
        super().__init__()
        hcg, mp = _mp_info()
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.bias = (self.create_parameter(
            [out_features], default_initializer=I.Constant(0.0), is_bias=True)
            if has_bias else None)
        if hcg is not None:
            _place(self.weight, hcg.mesh, P("mp", None))

    def forward(self, x):
        hcg, mp = _mp_info()
        if hcg is not None:
            x = Tensor(_constrain(
                x._data if isinstance(x, Tensor) else x, hcg.mesh,
                P(None, None, "mp")))
        out = F.linear(x, self.weight, self.bias)
        return ReduceScatterOp.apply(out)  # partial-sum -> seq-sharded
