"""fleet.init / distributed_model / distributed_optimizer
(reference: python/paddle/distributed/fleet/fleet.py:218, model.py:32).
"""

from __future__ import annotations

from typing import Optional

from ..parallel import init_parallel_env
from .base.distributed_strategy import DistributedStrategy
from .base.topology import (
    CommunicateTopology,
    HybridCommunicateGroup,
    ParallelMode,
    get_hcg,
    set_hcg,
)

_fleet_state = {"initialized": False, "strategy": None}


def init(role_maker=None, is_collective=True, strategy: Optional[DistributedStrategy] = None, log_level="INFO"):
    """Build the hybrid topology + per-axis groups (reference fleet.py:218 →
    topology.py:70). On TPU this also defines THE device mesh."""
    strategy = strategy or DistributedStrategy()
    hc = strategy.hybrid_configs
    topo = CommunicateTopology(
        hybrid_group_names=["data", "pipe", "sharding", "sep", "model"],
        dims=[
            hc.get("dp_degree", 1),
            hc.get("pp_degree", 1),
            hc.get("sharding_degree", 1),
            hc.get("sep_degree", 1),
            hc.get("mp_degree", 1),
        ],
    )
    init_parallel_env()
    hcg = HybridCommunicateGroup(topo)
    set_hcg(hcg)
    _fleet_state["initialized"] = True
    _fleet_state["strategy"] = strategy
    return hcg


def get_hybrid_communicate_group() -> HybridCommunicateGroup:
    return get_hcg()


def distributed_model(model):
    """Wrap a Layer for the active parallel mode (reference fleet/model.py:32)."""
    hcg = get_hcg()
    if hcg is None:
        return model
    mode = hcg.get_parallel_mode()
    from .meta_parallel.parallel_layers.pp_layers import PipelineLayer
    from .meta_parallel.pipeline_parallel import PipelineParallel
    from .meta_parallel.sharding_parallel import ShardingParallel
    from .meta_parallel.segment_parallel import SegmentParallel
    from .meta_parallel.tensor_parallel import TensorParallel

    if mode == ParallelMode.PIPELINE_PARALLEL or isinstance(model, PipelineLayer):
        return PipelineParallel(model, hcg, _fleet_state["strategy"])
    if mode == ParallelMode.SHARDING_PARALLEL:
        return ShardingParallel(model, hcg, _fleet_state["strategy"])
    if mode == ParallelMode.SEGMENT_PARALLEL:
        return SegmentParallel(model, hcg, _fleet_state["strategy"])
    if mode == ParallelMode.TENSOR_PARALLEL:
        return TensorParallel(model, hcg, _fleet_state["strategy"])
    from ..parallel import DataParallel

    return DataParallel(model)


def distributed_optimizer(optimizer, strategy=None):
    from .meta_optimizers.dygraph_optimizer.hybrid_parallel_optimizer import (
        HybridParallelOptimizer,
    )

    hcg = get_hcg()
    if hcg is None:
        return optimizer
    return HybridParallelOptimizer(optimizer, hcg, strategy or _fleet_state["strategy"])


def is_initialized():
    return _fleet_state["initialized"]


class worker_index:
    def __new__(cls):
        from ..parallel import get_rank

        return get_rank()


def worker_num():
    from ..parallel import get_world_size

    return get_world_size()
