"""5-D hybrid topology (reference: python/paddle/distributed/fleet/base/topology.py:70,189).

Axes order matches the reference: [data, pipe, sharding, sep, model]. TPU-native: the
topology materializes as ONE jax Mesh with named axes; per-axis "comm groups" are
Group handles bound to those axis names (collectives over them ride ICI). The
reference's careful axis ordering (model innermost = fastest-varying ranks) maps to
mesh axis order so that 'mp'/'sep' land on the innermost ICI torus dimension.
"""

from __future__ import annotations

import collections
import itertools
from functools import reduce
from typing import List

import jax
import numpy as np

from ...communication.group import Group, new_group

_HYBRID_PARALLEL_ORDER = ["data", "pipe", "sharding", "sep", "model"]


class CommunicateTopology:
    def __init__(self, hybrid_group_names=None, dims=None):
        self._parallel_names = list(hybrid_group_names or _HYBRID_PARALLEL_ORDER)
        self._dims = list(dims or [1] * len(self._parallel_names))
        self.coordinate = collections.namedtuple("Coordinate", self._parallel_names)
        self.world_size = int(np.prod(self._dims))
        ranges = [range(d) for d in self._dims]
        all_coords = [self.coordinate(*c) for c in itertools.product(*ranges)]
        self._coord2rank = dict(zip(all_coords, range(len(all_coords))))
        self._rank2coord = dict(zip(self._coord2rank.values(), self._coord2rank.keys()))

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def get_rank(self, **args):
        assert len(args) == len(self._dims)
        return self._coord2rank[self.coordinate(**args)]

    def get_coord(self, rank):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        return sorted(r for c, r in self._coord2rank.items() if c[axis] == index)

    def get_comm_list(self, axis_name):
        """All rank-groups along axis_name (reference topology.py get_comm_list)."""
        axis = self._parallel_names.index(axis_name)
        other_ranges = [range(d) for i, d in enumerate(self._dims) if i != axis]
        comm_list = []
        for other in itertools.product(*other_ranges):
            ranks = []
            for v in range(self._dims[axis]):
                coord = list(other)
                coord.insert(axis, v)
                ranks.append(self._coord2rank[self.coordinate(*coord)])
            comm_list.append(ranks)
        return comm_list

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = self.get_coord(global_rank)
        tf = coord._replace(**kwargs)._asdict()
        return self.get_rank(**tf)


class HybridCommunicateGroup:
    """Reference: topology.py:189. Holds per-axis Group handles + the global Mesh."""

    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        self.nranks = topology.world_size
        self.global_rank = 0  # single-controller SPMD; multihost uses process_index
        self._dp_degree = topology.get_dim("data")
        self._pp_degree = topology.get_dim("pipe")
        self._sharding_degree = topology.get_dim("sharding")
        self._sep_degree = topology.get_dim("sep") if "sep" in topology.get_hybrid_group_names() else 1
        self._mp_degree = topology.get_dim("model")

        # one global mesh with named axes, in topology order
        names = topology.get_hybrid_group_names()
        dims = [topology.get_dim(n) for n in names]
        self._axis_names = {"data": "dp", "pipe": "pp", "sharding": "sharding", "sep": "sep", "model": "mp"}
        n_needed = int(np.prod(dims))
        devs = jax.devices()
        assert n_needed <= len(devs), f"topology needs {n_needed} devices, have {len(devs)}"
        mesh_axes = tuple(self._axis_names[n] for n in names)
        self.mesh = jax.sharding.Mesh(np.array(devs[:n_needed]).reshape(dims), mesh_axes)

        def make_group(axis):
            ranks = self._topo.get_comm_list(axis)[0]
            return new_group(ranks, axis_name=self._axis_names[axis], mesh=self.mesh)

        self._dp_group = make_group("data")
        self._pp_group = make_group("pipe")
        self._sharding_group = make_group("sharding")
        self._sep_group = make_group("sep") if self._sep_degree > 1 or "sep" in names else None
        self._mp_group = make_group("model")
        # fused dp+sharding group (reference: dp_sharding fused axes)
        self._dp_sharding_group = self._dp_group

    # ---- degrees ----
    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    # ---- ranks (single-controller: rank 0 views) ----
    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_sharding_parallel_rank(self):
        return 0

    def get_sep_parallel_rank(self):
        return 0

    # ---- groups ----
    def get_data_parallel_group(self):
        return self._dp_group

    def get_model_parallel_group(self):
        return self._mp_group

    def get_pipe_parallel_group(self):
        return self._pp_group

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_sep_parallel_group(self):
        return self._sep_group

    def get_check_parallel_group(self, sharding=False):
        return self._mp_group

    def get_data_parallel_group_src_rank(self):
        return self._dp_group.ranks[0]

    def get_model_parallel_group_src_rank(self):
        return self._mp_group.ranks[0]

    # ---- pipeline helpers ----
    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1

    def get_p2p_groups(self):
        return None

    def topology(self):
        return self._topo

    def get_parallel_mode(self):
        from . import topology as _t

        if self._mp_degree == 1 and self._pp_degree == 1 and self._sharding_degree == 1 and self._dp_degree > 1:
            return ParallelMode.DATA_PARALLEL
        if self._sharding_degree > 1 and self._mp_degree == 1 and self._pp_degree == 1:
            return ParallelMode.SHARDING_PARALLEL
        if self._pp_degree > 1:
            return ParallelMode.PIPELINE_PARALLEL
        if self._sep_degree > 1:
            return ParallelMode.SEGMENT_PARALLEL
        return ParallelMode.TENSOR_PARALLEL


class ParallelMode:
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3
    SEGMENT_PARALLEL = 4


_hcg: List[HybridCommunicateGroup] = []


def set_hcg(hcg):
    _hcg.clear()
    _hcg.append(hcg)


def get_hcg():
    return _hcg[0] if _hcg else None
