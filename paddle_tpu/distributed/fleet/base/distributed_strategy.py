"""DistributedStrategy (reference: python/paddle/distributed/fleet/base/distributed_strategy.py,
backed by fluid/framework/distributed_strategy.proto).

Python-dict config tree instead of protobuf — same keys, TPU-relevant semantics.
"""

from __future__ import annotations

import copy


class DistributedStrategy:
    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1,
            "mp_degree": 1,
            "pp_degree": 1,
            "sharding_degree": 1,
            "sep_degree": 1,
            "order": ["dp", "pp", "sharding", "sep", "mp"],
            "mp_configs": {},
            "pp_configs": {},
        }
        self.sharding = False
        self.sharding_configs = {
            "sharding_degree": 1,
            "stage": 1,
            "offload": False,
            "comm_overlap": True,
        }
        self.pipeline = False
        self.pipeline_configs = {
            "accumulate_steps": 1,
            "micro_batch_size": 1,
            "schedule_mode": "1F1B",
            "enable_partial_send_recv": True,
        }
        self.amp = False
        self.amp_configs = {
            "init_loss_scaling": 32768.0,
            "use_pure_fp16": False,
            "use_bf16": True,
            "custom_white_list": [],
            "custom_black_list": [],
        }
        self.recompute = False
        self.recompute_configs = {"checkpoints": []}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        self.lamb = False
        self.lars = False
        self.dgc = False
        self.localsgd = False
        self.heter_ccl_mode = False
        self.find_unused_parameters = False
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.gradient_scale_configs = {"scale_strategy": "avg"}

    def __deepcopy__(self, memo):
        new = DistributedStrategy()
        for k, v in self.__dict__.items():
            setattr(new, k, copy.deepcopy(v, memo))
        return new

    def __repr__(self):
        lines = ["DistributedStrategy:"]
        for k, v in self.__dict__.items():
            lines.append(f"  {k}: {v}")
        return "\n".join(lines)
