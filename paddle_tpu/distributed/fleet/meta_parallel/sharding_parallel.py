"""ShardingParallel wrapper (reference: fleet/meta_parallel/sharding_parallel.py).

ZeRO sharding on TPU = parameter/grad/opt-state NamedSharding over the 'sharding'
mesh axis (see meta_optimizers.dygraph_optimizer.DygraphShardingOptimizer); the
model wrapper itself is pass-through.
"""

from ....nn.layer.layers import Layer


class ShardingParallel(Layer):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, sd, *args, **kwargs):
        return self._layers.set_state_dict(sd, *args, **kwargs)
