"""Tensor-parallel layers (reference: fleet/layers/mpu/mp_layers.py —
VocabParallelEmbedding:47, ColumnParallelLinear:334, RowParallelLinear:541,
ParallelCrossEntropy:742).

TPU-native: instead of hand-placed allreduces around sharded matmuls, the weights
carry a NamedSharding over the 'mp' mesh axis and forward adds sharding constraints;
GSPMD inserts the matching collectives (all-gather / reduce-scatter / all-reduce)
and XLA's latency-hiding scheduler overlaps them with MXU work. The math and the
weight partitioning are identical to the reference (column = shard out-features,
row = shard in-features).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .....core.tensor import Tensor
from .....nn import functional as F
from .....nn import initializer as I
from .....nn.layer.layers import Layer
from ...base.topology import get_hcg


def _mp_info():
    hcg = get_hcg()
    if hcg is None or hcg.get_model_parallel_world_size() <= 1:
        return None, 1
    return hcg, hcg.get_model_parallel_world_size()


def _place(param: Tensor, mesh, spec):
    if mesh is not None and not isinstance(param._data, jax.core.Tracer):
        param._data = jax.device_put(param._data, NamedSharding(mesh, spec))
    param._mp_spec = spec


def _constrain(arr, mesh, spec):
    if mesh is None:
        return arr
    try:
        return jax.lax.with_sharding_constraint(arr, NamedSharding(mesh, spec))
    except Exception:
        return arr


class VocabParallelEmbedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, weight_attr=None, mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        hcg, ws = _mp_info()
        self.world_size = ws
        self.mesh = hcg.mesh if hcg else None
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr, default_initializer=I.XavierNormal()
        )
        _place(self.weight, self.mesh, P("mp", None))

    def forward(self, x):
        return F.embedding(x, self.weight)


class ColumnParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 gather_output=True, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        hcg, ws = _mp_info()
        self.world_size = ws
        self.mesh = hcg.mesh if hcg else None
        self.weight = self.create_parameter([in_features, out_features], attr=weight_attr)
        _place(self.weight, self.mesh, P(None, "mp"))
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            _place(self.bias, self.mesh, P("mp"))
        else:
            self.bias = None

    def forward(self, x):
        from .....core.op_registry import apply_fn, OpDef, AMP_WHITE

        mesh, gather = self.mesh, self.gather_output

        def fn(a, w, *b):
            out = jnp.matmul(a, w)
            if b:
                out = out + b[0]
            if mesh is not None:
                spec = P(*([None] * (out.ndim - 1)), None if gather else "mp")
                out = _constrain(out, mesh, spec)
            return out

        args = [x, self.weight] + ([self.bias] if self.bias is not None else [])
        return apply_fn("column_parallel_linear", fn, *args, _opdef=_MM_DEF)


_MM_DEF = None


def _init_mm_def():
    global _MM_DEF
    from .....core.op_registry import AMP_WHITE, OpDef

    _MM_DEF = OpDef("column_parallel_linear", None, amp=AMP_WHITE)


_init_mm_def()


class RowParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        hcg, ws = _mp_info()
        self.world_size = ws
        self.mesh = hcg.mesh if hcg else None
        self.weight = self.create_parameter([in_features, out_features], attr=weight_attr)
        _place(self.weight, self.mesh, P("mp", None))
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            _place(self.bias, self.mesh, P())
        else:
            self.bias = None

    def forward(self, x):
        from .....core.op_registry import apply_fn

        mesh = self.mesh

        def fn(a, w, *b):
            if mesh is not None:
                # contract over the sharded dim — GSPMD emits the all-reduce
                a = _constrain(a, mesh, P(*([None] * (a.ndim - 1)), "mp"))
            out = jnp.matmul(a, w)
            if mesh is not None:
                out = _constrain(out, mesh, P(*([None] * out.ndim)))
            if b:
                out = out + b[0]
            return out

        args = [x, self.weight] + ([self.bias] if self.bias is not None else [])
        return apply_fn("row_parallel_linear", fn, *args, _opdef=_MM_DEF)


class ParallelCrossEntropy(Layer):
    """Reference mp_layers.py:742 — CE over vocab-sharded logits. GSPMD computes the
    log-softmax reduction with a cross-'mp' all-reduce automatically."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label, soft_label=False):
        loss = F.cross_entropy(input, label, soft_label=soft_label,
                               ignore_index=self.ignore_index, reduction="none")
        return loss
