"""Pipeline layer description (reference: fleet/meta_parallel/parallel_layers/pp_layers.py —
LayerDesc, SharedLayerDesc, PipelineLayer:257).

TPU-native: the stage partition is a *logical* split. In single-controller SPMD all
stages live in one program; the PipelineParallel engine (pipeline_parallel.py) builds
a shard_map over the 'pp' mesh axis where each device executes only its stage's
layers and activations move along ppermute edges.
"""

from __future__ import annotations

import math
from typing import Callable, List, Optional

from .....nn.layer.layers import Layer, LayerList


class LayerDesc:
    def __init__(self, layer_class, *inputs, **kwargs):
        self.layer_class = layer_class
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_class, Layer):
            raise TypeError("LayerDesc expects an nn.Layer subclass")

    def build_layer(self):
        return self.layer_class(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_class.__name__})"


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_class, forward_func=None, shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_class, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    def __init__(self, layers_desc, num_parts, method="uniform", num_virtual_pipeline_stage=None):
        self.layers_desc = layers_desc
        self.num_parts = num_parts
        self.method = method

    def do_segment(self) -> List[int]:
        n = len(self.layers_desc)
        if self.method == "uniform":
            base = n // self.num_parts
            extra = n % self.num_parts
            bounds = [0]
            for i in range(self.num_parts):
                bounds.append(bounds[-1] + base + (1 if i < extra else 0))
            return bounds
        if self.method.startswith("layer:"):
            # put every layer whose class name matches evenly; others attach to stages
            name = self.method.split(":", 1)[1]
            marks = [i for i, d in enumerate(self.layers_desc)
                     if (isinstance(d, LayerDesc) and d.layer_class.__name__ == name)]
            per = len(marks) // self.num_parts
            bounds = [0]
            for i in range(1, self.num_parts):
                bounds.append(marks[i * per])
            bounds.append(len(self.layers_desc))
            return bounds
        raise ValueError(f"unknown segment method {self.method}")


class PipelineLayer(Layer):
    """Declarative stage-partitioned model (reference pp_layers.py:257)."""

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, num_virtual_pipeline_stages=None, **kwargs):
        super().__init__()
        self._layers_desc = list(layers)
        self._loss_fn = loss_fn
        self._recompute_interval = recompute_interval
        from ...base.topology import get_hcg

        hcg = get_hcg()
        if num_stages is None and hcg is not None:
            num_stages = hcg.get_pipe_parallel_world_size()
        self._num_stages = num_stages or 1
        seg = SegmentLayers(self._layers_desc, self._num_stages, seg_method)
        self.segment_parts = seg.do_segment()
        # single-controller: build ALL layers; the pipeline engine selects per stage
        self.run_function: List = []
        self._shared = {}
        built = []
        for i, d in enumerate(self._layers_desc):
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    base = self._shared[d.layer_name]
                    fwd = d.forward_func
                    layer = _SharedForward(base, fwd)
                else:
                    layer = d.build_layer()
                    self._shared[d.layer_name] = layer
                built.append(layer)
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif isinstance(d, Layer):
                built.append(d)
            elif callable(d):
                built.append(_FnLayer(d))
            else:
                raise TypeError(f"bad layer desc: {d}")
        self.layers_holder = LayerList([l for l in built if isinstance(l, Layer)])
        self.run_function = built

    def get_stage_layers(self, stage_id: int):
        lo, hi = self.segment_parts[stage_id], self.segment_parts[stage_id + 1]
        return self.run_function[lo:hi]

    def forward(self, x):
        for fn in self.run_function:
            x = fn(x)
        return x

    def loss(self, output, label):
        return self._loss_fn(output, label) if self._loss_fn else output

    @property
    def parameters_in_stage(self):
        return {s: [p for l in self.get_stage_layers(s) if isinstance(l, Layer) for p in l.parameters()]
                for s in range(self._num_stages)}


class _FnLayer(Layer):
    def __init__(self, fn: Callable):
        super().__init__()
        self._fn = fn

    def forward(self, *args, **kwargs):
        return self._fn(*args, **kwargs)


class _SharedForward(Layer):
    def __init__(self, base: Layer, fwd: Optional[Callable]):
        super().__init__()
        self._base = base  # NOTE: registered as sublayer -> weights shared by identity
        self._fwd = fwd

    def forward(self, x):
        if self._fwd is not None:
            return self._fwd(self._base, x)
        return self._base(x)
