"""TP-aware RNG (reference: fleet/layers/mpu/random.py:34 RNGStatesTracker).

On TPU, per-mesh-axis decorrelated randomness is achieved by folding the mesh
coordinates into the PRNG key rather than tracking per-rank cuRAND states.
"""

from __future__ import annotations

import contextlib

import jax

from .....framework.random import get_rng_state, rng_guard

MODEL_PARALLEL_RNG = "model_parallel_rng"


class RNGStatesTracker:
    def __init__(self):
        self.states_ = {}

    def reset(self):
        self.states_.clear()

    def add(self, name, seed):
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        self.states_[name] = jax.random.key(seed)

    @contextlib.contextmanager
    def rng_state(self, name=MODEL_PARALLEL_RNG):
        if name not in self.states_:
            # derive deterministically from the global key + name hash
            self.states_[name] = jax.random.fold_in(get_rng_state(), abs(hash(name)) % (2**31))
        key = self.states_[name]
        k1, k2 = jax.random.split(key)
        self.states_[name] = k1
        with rng_guard(k2):
            yield


_tracker = RNGStatesTracker()


def get_rng_state_tracker():
    return _tracker


def model_parallel_random_seed(seed=None):
    import numpy as np

    from ...base.topology import get_hcg

    hcg = get_hcg()
    mp_rank = hcg.get_model_parallel_rank() if hcg else 0
    base = seed if seed is not None else np.random.randint(0, 2**20)
    _tracker.reset()
    _tracker.add(MODEL_PARALLEL_RNG, base + 1024 + mp_rank)
