"""SegmentParallel / SEP engine (reference: fleet/meta_parallel/segment_parallel.py:26).

Ulysses-class sequence sharding: activations sharded over the 'sep' mesh axis on the
sequence dim; attention does head<->sequence all_to_all (see
fleet/utils/sequence_parallel_utils.sep_all_to_all). Param broadcast across sep is
moot in single-controller SPMD.
"""

from ....nn.layer.layers import Layer


class SegmentParallel(Layer):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, sd, *args, **kwargs):
        return self._layers.set_state_dict(sd, *args, **kwargs)
