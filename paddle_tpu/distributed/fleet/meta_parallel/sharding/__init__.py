"""Import-path parity with the reference's
fleet/meta_parallel/sharding/group_sharded_stage{2,3}.py and
group_sharded_optimizer_stage2.py — implementations live in
paddle_tpu.distributed.sharding.group_sharded (sharding-spec semantics)."""

from ....sharding.group_sharded import (  # noqa: F401
    GroupShardedStage2,
    GroupShardedStage3,
    _ShardedOptimizer as GroupShardedOptimizerStage2,
)
