"""Pipeline-parallel engine (reference: fleet/meta_parallel/pipeline_parallel.py:231,
forward_backward_pipeline:547 — 1F1B, interleave variants :1143,:1972;
p2p via pp_utils/p2p_communication.py).

TPU-native redesign: the reference runs per-rank Python schedules exchanging
activations over NCCL P2P with shape negotiation (SendRecvMeta). Under XLA we express
the *whole* pipeline as one compiled program:

  - ``train_batch`` (single-controller convenience): microbatch loop with gradient
    accumulation — every stage's layers live in one program; XLA overlaps compute.
  - ``pipeline_spmd_step`` (the scalable path, used by dryrun_multichip and the
    Llama trainer): shard_map over the 'pp' mesh axis; each device executes only its
    stage's weights; activations circulate via lax.ppermute; the schedule is a
    lax.scan over (num_micro + num_stages - 1) ticks = GPipe fill/drain. Backward
    falls out of jax.grad through scan+ppermute — the transpose of ppermute is the
    reverse rotation, giving the reverse pipeline automatically (no hand-written
    1F1B state machine, no SendRecvMeta: shapes are static under jit).
"""

from __future__ import annotations

from typing import Callable, List

import jax
import jax.numpy as jnp

from ....core.tensor import Tensor
from ....nn.layer.layers import Layer
from .parallel_layers.pp_layers import PipelineLayer


class PipelineParallel(Layer):
    def __init__(self, layers: PipelineLayer, hcg, strategy=None):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        pc = (strategy.pipeline_configs if strategy is not None else {}) or {}
        self._micro_batch_size = pc.get("micro_batch_size", 1)
        self._accumulate_steps = pc.get("accumulate_steps", 1)
        self.num_stages = hcg.get_pipe_parallel_world_size()

    def forward(self, x):
        return self._layers(x)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Microbatched fwd/bwd with grad accumulation (logical 1F1B; in SPMD all
        stages share the controller so the schedule is a dependency graph XLA
        pipelines)."""
        x, y = data
        n_micro = self._accumulate_steps
        bsz = x.shape[0]
        micro = max(bsz // n_micro, 1)
        total = None
        optimizer.clear_grad()
        for i in range(n_micro):
            xb = x[i * micro:(i + 1) * micro]
            yb = y[i * micro:(i + 1) * micro]
            out = self._layers(xb)
            loss = self._layers.loss(out, yb)
            scaled = loss / n_micro if n_micro > 1 else loss
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            total = float(loss.numpy()) if total is None else total + float(loss.numpy())
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return Tensor(jnp.asarray(total / n_micro))

    def eval_batch(self, data, compute_loss=True):
        x, y = data
        out = self._layers(x)
        return self._layers.loss(out, y) if compute_loss else out


def gpipe_spmd(stage_fn: Callable, n_stages: int, axis_name: str = "pp"):
    """Build a jit-able GPipe executor over a mesh axis.

    stage_fn(stage_params, x) -> y runs ONE stage's computation. Returns
    ``pipeline(stacked_params, micro_inputs) -> micro_outputs`` to be called INSIDE
    shard_map where `axis_name` is bound: stacked_params has a leading stage axis
    sharded over `axis_name`; micro_inputs is [n_micro, ...] (replicated).

    Ticks: t in [0, n_micro + n_stages - 1). Stage 0 injects microbatch t; stage
    s>0 consumes its neighbor's previous output via ppermute; outputs drain from the
    last stage. Differentiable end-to-end (scan + ppermute transpose).
    """

    def pipeline(params, micro_inputs):
        n_micro = micro_inputs.shape[0]
        stage = jax.lax.axis_index(axis_name)
        total_ticks = n_micro + n_stages - 1
        x_shape = micro_inputs.shape[1:]
        dtype = micro_inputs.dtype
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            buf_in, outputs = carry
            # stage 0 reads microbatch t (or zeros in drain phase)
            mb_idx = jnp.clip(t, 0, n_micro - 1)
            inject = jax.lax.dynamic_index_in_dim(micro_inputs, mb_idx, 0, keepdims=False)
            x = jnp.where(stage == 0, inject, buf_in)
            active = (t - stage >= 0) & (t - stage < n_micro)
            y = stage_fn(params, x)
            y = jnp.where(active, y, jnp.zeros_like(y))
            # last stage writes its result into the output slot for microbatch t-stage
            out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            is_out = (stage == n_stages - 1) & (t >= n_stages - 1)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(is_out, y, jax.lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)),
                out_idx, 0,
            )
            nxt = jax.lax.ppermute(y, axis_name, perm)
            return (nxt, outputs), None

        buf0 = jnp.zeros(x_shape, dtype)
        outs0 = jnp.zeros((n_micro,) + x_shape, dtype)
        (_, outputs), _ = jax.lax.scan(tick, (buf0, outs0), jnp.arange(total_ticks))
        return outputs

    return pipeline
