"""Pipeline-parallel engine (reference: fleet/meta_parallel/pipeline_parallel.py:231,
forward_backward_pipeline:547 — 1F1B, interleave variants :1143,:1972;
p2p via pp_utils/p2p_communication.py).

TPU-native redesign: the reference runs per-rank Python schedules exchanging
activations over NCCL P2P with shape negotiation (SendRecvMeta). Under XLA we express
the *whole* pipeline as one compiled program:

  - ``train_batch`` (single-controller convenience): microbatch loop with gradient
    accumulation — every stage's layers live in one program; XLA overlaps compute.
  - ``pipeline_spmd_step`` (the scalable path, used by dryrun_multichip and the
    Llama trainer): shard_map over the 'pp' mesh axis; each device executes only its
    stage's weights; activations circulate via lax.ppermute; the schedule is a
    lax.scan over (num_micro + num_stages - 1) ticks = GPipe fill/drain. Backward
    falls out of jax.grad through scan+ppermute — the transpose of ppermute is the
    reverse rotation, giving the reverse pipeline automatically (no hand-written
    1F1B state machine, no SendRecvMeta: shapes are static under jit).
"""

from __future__ import annotations

from typing import Callable, List

import jax
import jax.numpy as jnp

from ....core.tensor import Tensor
from ....nn.layer.layers import Layer
from .parallel_layers.pp_layers import PipelineLayer


class PipelineParallel(Layer):
    def __init__(self, layers: PipelineLayer, hcg, strategy=None):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        pc = (strategy.pipeline_configs if strategy is not None else {}) or {}
        self._micro_batch_size = pc.get("micro_batch_size", 1)
        self._accumulate_steps = pc.get("accumulate_steps", 1)
        self.num_stages = hcg.get_pipe_parallel_world_size()

    def forward(self, x):
        return self._layers(x)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Microbatched fwd/bwd with grad accumulation (logical 1F1B; in SPMD all
        stages share the controller so the schedule is a dependency graph XLA
        pipelines)."""
        x, y = data
        n_micro = self._accumulate_steps
        bsz = x.shape[0]
        micro = max(bsz // n_micro, 1)
        total = None
        optimizer.clear_grad()
        for i in range(n_micro):
            xb = x[i * micro:(i + 1) * micro]
            yb = y[i * micro:(i + 1) * micro]
            out = self._layers(xb)
            loss = self._layers.loss(out, yb)
            scaled = loss / n_micro if n_micro > 1 else loss
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            total = float(loss.numpy()) if total is None else total + float(loss.numpy())
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return Tensor(jnp.asarray(total / n_micro))

    def eval_batch(self, data, compute_loss=True):
        x, y = data
        out = self._layers(x)
        return self._layers.loss(out, y) if compute_loss else out


def gpipe_spmd(stage_fn: Callable, n_stages: int, axis_name: str = "pp"):
    """Build a jit-able GPipe executor over a mesh axis (to call INSIDE shard_map
    where `axis_name` is manual). Thin alias of the shared schedule in
    ``paddle_tpu.distributed.auto_parallel.pipeline.gpipe_schedule``; the
    full-featured path (stacked per-layer params, remat, auto axes) is
    ``pipeline_call`` in the same module.
    """
    from ...auto_parallel.pipeline import gpipe_schedule

    return gpipe_schedule(stage_fn, n_stages, axis_name)
