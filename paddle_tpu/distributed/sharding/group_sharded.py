"""Group-sharded (ZeRO) API — reference:
/root/reference/python/paddle/distributed/sharding/group_sharded.py:50
``group_sharded_parallel(model, optimizer, level='os'|'os_g'|'p_g_os', ...)``
backed by GroupShardedStage2/Stage3 + GroupShardedOptimizerStage2
(fleet/meta_parallel/sharding/group_sharded_stage{2,3}.py:46,:85).

TPU-native collapse: the three ZeRO stages are all *sharding specs*, not
runtime choreography.

  - 'os'     (stage 1): optimizer accumulators sharded dim-0 over the axis.
  - 'os_g'   (stage 2): + gradients sharded — under jit the grads already carry
    the param sharding (GSPMD reduce-scatters instead of all-reducing); eagerly
    grads are placed with the same sharding as the accumulators.
  - 'p_g_os' (stage 3): + parameters sharded dim-0 — XLA all-gathers a weight
    just-in-time where a consumer needs it and frees it after (the on-demand
    allgather the reference implements by hand in GroupShardedStage3).

The reference's bucketing (buffer_max_size), segment_size, sync_comm, offload
knobs are accepted for API compatibility; buffering/overlap is XLA's
latency-hiding scheduler's job. ``offload=True`` pins accumulators to host
memory (jax.device_put to the CPU backend).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _sharding_axis(group=None):
    """Resolve (mesh, axis_name) for the sharding axis.

    Priority: explicit group's mesh/axis → active fleet HCG ('sharding') →
    current logical-sharding mesh ('fsdp' or 'sharding' axis if present).
    """
    if group is not None and getattr(group, "mesh", None) is not None:
        return group.mesh, group.axis_name
    from ..fleet.fleet import get_hybrid_communicate_group

    try:
        hcg = get_hybrid_communicate_group()
    except Exception:
        hcg = None
    if hcg is not None and hcg.get_sharding_parallel_world_size() > 1:
        return hcg.mesh, "sharding"
    from ..auto_parallel.logical_sharding import current_mesh

    mesh = current_mesh()
    if mesh is not None:
        for name in ("sharding", "fsdp", "dp"):
            if name in mesh.axis_names and mesh.shape[name] > 1:
                return mesh, name
    return None, None


def _dim0_sharding(mesh: Mesh, axis: str, arr) -> Optional[NamedSharding]:
    if arr.ndim == 0 or arr.shape[0] % mesh.shape[axis] != 0:
        return NamedSharding(mesh, P())  # not evenly shardable -> replicate
    return NamedSharding(mesh, P(axis, *([None] * (arr.ndim - 1))))


def install_sharded_accumulators(optimizer, mesh: Mesh, axis: str,
                                 offload: bool = False) -> None:
    """Monkey-patch ``optimizer._acc`` so every accumulator is created with a
    dim-0 sharding over ``axis`` (ZeRO-1). The single implementation behind
    _ShardedOptimizer and fleet's DygraphShardingOptimizer.

    ``offload=True`` additionally places accumulators in host memory via the
    ``pinned_host`` memory kind (XLA host-offload; falls back to device memory
    on backends without it — CPU tests exercise the sharding path only).
    """
    orig_acc = optimizer._acc

    def _sharding_for(arr):
        sh = _dim0_sharding(mesh, axis, arr)
        if offload:
            try:
                sh = sh.with_memory_kind("pinned_host")
            except Exception:
                pass
        return sh

    def _acc(name, p, init=None, dtype=None):
        arr = orig_acc(name, p, init, dtype)
        if isinstance(arr, jax.core.Tracer) or arr.ndim == 0:
            return arr
        try:
            arr = jax.device_put(arr, _sharding_for(arr))
        except Exception:
            if not offload:
                raise
            arr = jax.device_put(arr, _dim0_sharding(mesh, axis, arr))
        optimizer._accumulators[name][id(p)] = arr
        return arr

    optimizer._acc = _acc


class _ShardedOptimizer:
    """Wraps an Optimizer so accumulators (and their checkpoints) are sharded."""

    def __init__(self, inner, mesh: Mesh, axis: str, offload: bool = False):
        self._inner_opt = inner
        self._mesh = mesh
        self._axis = axis
        self._offload = offload
        install_sharded_accumulators(inner, mesh, axis, offload)

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        return self._inner_opt.step()

    def clear_grad(self, set_to_zero=True):
        return self._inner_opt.clear_grad()

    clear_gradients = clear_grad


class GroupShardedStage2:
    """Model wrapper for 'os_g' (reference group_sharded_stage2.py:46): grads
    are placed with the accumulator sharding as they are produced."""

    def __init__(self, layer, mesh, axis, sync_buffers=False):
        self._layers = layer
        self._mesh = mesh
        self._axis = axis
        self._register_grad_hooks()

    def _register_grad_hooks(self):
        mesh, axis = self._mesh, self._axis

        def make_hook(p):
            def hook(grad):
                from ...core.tensor import Tensor

                g = grad._data if isinstance(grad, Tensor) else grad
                if not isinstance(g, jax.core.Tracer):
                    g = jax.device_put(g, _dim0_sharding(mesh, axis, g))
                    return Tensor(g) if isinstance(grad, Tensor) else g
                return grad

            return hook

        for p in self._layers.parameters():
            if not p.stop_gradient:
                p.register_hook(make_hook(p))

    def __call__(self, *a, **kw):
        return self._layers(*a, **kw)

    def __getattr__(self, item):
        return getattr(self._layers, item)


class GroupShardedStage3(GroupShardedStage2):
    """'p_g_os' (reference group_sharded_stage3.py:85): parameters sharded
    dim-0; XLA all-gathers on demand (the hand-written broadcast in the
    reference's forward hooks)."""

    def __init__(self, layer, mesh, axis, sync_buffers=False):
        for p in layer.parameters():
            if not isinstance(p._data, jax.core.Tracer) and p._data.ndim > 0:
                p._data = jax.device_put(p._data, _dim0_sharding(mesh, axis, p._data))
        super().__init__(layer, mesh, axis, sync_buffers)


def group_sharded_parallel(
    model,
    optimizer,
    level: str,
    scaler=None,
    group=None,
    offload: bool = False,
    sync_buffers: bool = False,
    buffer_max_size: int = 2**23,
    segment_size: int = 2**20,
    sync_comm: bool = False,
    dp_group=None,
    exclude_layer: Optional[Sequence] = None,
):
    """Reference group_sharded.py:50 — same signature, sharding-spec semantics."""
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError("level must be one of 'os' | 'os_g' | 'p_g_os'")
    mesh, axis = _sharding_axis(group)
    if mesh is None:
        # no sharding axis available (single device): return unwrapped
        return model, optimizer, scaler

    if level in ("os_g", "p_g_os"):
        wrapper = GroupShardedStage3 if level == "p_g_os" else GroupShardedStage2
        model = wrapper(model, mesh, axis, sync_buffers=sync_buffers)
    optimizer = _ShardedOptimizer(optimizer, mesh, axis, offload=offload)
    return model, optimizer, scaler


def save_group_sharded_model(model, output: str, optimizer=None) -> None:
    """Reference group_sharded.py:199 — save the unwrapped model/optimizer."""
    import os

    from ...framework import io as fio

    inner = getattr(model, "_layers", model)
    os.makedirs(output, exist_ok=True)
    fio.save(inner.state_dict(), os.path.join(output, "model.pdmodel"))
    if optimizer is not None:
        opt = getattr(optimizer, "_inner_opt", optimizer)
        fio.save(opt.state_dict(), os.path.join(output, "model.pdopt"))
