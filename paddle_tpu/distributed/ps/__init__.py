"""paddle_tpu.distributed.ps — parameter-server training, host-side emulation.

Parity anchors: the reference's brpc PS stack
(/root/reference/paddle/fluid/distributed/ps/service/brpc_ps_server.h,
ps/table/memory_sparse_table.h dense/sparse tables with server-side
optimizers, python/paddle/distributed/ps/ glue).

Scope note (TPU-native): the reference's PS mode exists for CPU-cluster
trillion-parameter embedding models. On TPU pods the same workload is served
by sharded embedding tables over ICI (expert/embedding sharding in the SPMD
engine). This module provides a functional host-side PS — dense/sparse tables
with server-side SGD/Adagrad, push/pull over the RPC layer, and (round 5)
``ShardedPsClient``: sparse feature ids sharded ``fid % n_servers`` across
multiple server processes with per-shard async fan-out — so PS-paradigm
programs port and small-scale PS jobs run; it intentionally does not
reimplement brpc/heter-PS CLUSTER scale-out. Cf. SURVEY.md §2 #30/#31.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from . import _tables
from .. import rpc

__all__ = ["ParameterServer", "PsWorker", "ShardedPsClient", "DenseTable",
           "SparseTable", "run_server", "stop_server"]

DenseTable = _tables.DenseTable
SparseTable = _tables.SparseTable


class ParameterServer:
    """Holds tables; methods are invoked remotely via the rpc layer."""

    def __init__(self):
        self._tables: Dict[str, object] = {}
        self._lock = threading.Lock()

    # -- table management --
    def create_dense_table(self, name: str, shape, optimizer="sgd", lr=0.01,
                           initializer="zeros"):
        with self._lock:
            existing = self._tables.get(name)
            if existing is None:
                self._tables[name] = DenseTable(shape, optimizer, lr, initializer)
            elif (not isinstance(existing, DenseTable)
                  or list(existing.value.shape) != [int(s) for s in shape]
                  or existing.optimizer != optimizer
                  or existing.lr != float(lr)
                  or existing.initializer != initializer):
                raise ValueError(
                    f"dense table '{name}' already exists with a different "
                    f"config: {existing.stat()}")
        return True

    def create_sparse_table(self, name: str, emb_dim: int, optimizer="adagrad",
                            lr=0.01, init_range=0.01):
        with self._lock:
            existing = self._tables.get(name)
            if existing is None:
                self._tables[name] = SparseTable(emb_dim, optimizer, lr,
                                                 init_range)
            elif (not isinstance(existing, SparseTable)
                  or existing.emb_dim != int(emb_dim)
                  or existing.optimizer != optimizer
                  or existing.lr != float(lr)
                  or existing.init_range != float(init_range)):
                raise ValueError(
                    f"sparse table '{name}' already exists with a different "
                    f"config: {existing.stat()}")
        return True

    def _table(self, name):
        # rpc handler threads race create_* (which mutates under the lock):
        # the lookup takes it too so a resize/replace never hands out a
        # half-registered table (PT-RACE-002, tools/lint_concurrency.py)
        with self._lock:
            return self._tables[name]

    # -- dense --
    def pull_dense(self, name: str) -> np.ndarray:
        return self._table(name).pull()

    def push_dense(self, name: str, grad: np.ndarray):
        self._table(name).push(grad)
        return True

    # -- sparse --
    def pull_sparse(self, name: str, ids: Sequence[int]) -> np.ndarray:
        return self._table(name).pull(ids)

    def push_sparse(self, name: str, ids: Sequence[int], grads: np.ndarray):
        self._table(name).push(ids, grads)
        return True

    def stat(self):
        with self._lock:
            return {n: t.stat() for n, t in self._tables.items()}


_server: Dict[str, Optional[ParameterServer]] = {"ps": None}


def run_server() -> ParameterServer:
    """Make this rpc worker a parameter server (reference:
    fleet.init(role).run_server for the PSERVER role)."""
    if _server["ps"] is None:
        _server["ps"] = ParameterServer()
    return _server["ps"]


def stop_server():
    _server["ps"] = None


def _dispatch(method: str, *args):
    ps = _server["ps"]
    if ps is None:
        raise RuntimeError("this worker is not a parameter server "
                           "(call ps.run_server() there)")
    return getattr(ps, method)(*args)


class PsWorker:
    """Trainer-side handle: push/pull against a named server worker
    (reference: the fleet worker role using BrpcPsClient)."""

    def __init__(self, server_name: str = "ps0"):
        self.server = server_name

    def _call(self, method, *args):
        return rpc.rpc_sync(self.server, _dispatch, args=(method,) + args)

    def create_dense_table(self, name, shape, optimizer="sgd", lr=0.01,
                           initializer="zeros"):
        return self._call("create_dense_table", name, list(shape), optimizer,
                          lr, initializer)

    def create_sparse_table(self, name, emb_dim, optimizer="adagrad", lr=0.01,
                            init_range=0.01):
        return self._call("create_sparse_table", name, emb_dim, optimizer, lr,
                          init_range)

    def pull_dense(self, name) -> np.ndarray:
        return self._call("pull_dense", name)

    def push_dense(self, name, grad) -> bool:
        return self._call("push_dense", name, np.asarray(grad))

    def pull_sparse(self, name, ids) -> np.ndarray:
        return self._call("pull_sparse", name, [int(i) for i in ids])

    def push_sparse(self, name, ids, grads) -> bool:
        return self._call("push_sparse", name, [int(i) for i in ids],
                          np.asarray(grads))

    def stat(self):
        return self._call("stat")


class ShardedPsClient:
    """Trainer-side handle over MULTIPLE parameter servers (round 5 —
    reference: the brpc PS shards sparse feature ids across server
    instances, ps/service/brpc_ps_client with per-shard request fan-out).

    Sharding scheme (the reference's):
      - sparse tables exist on EVERY server; feature id ``fid`` lives on
        server ``fid % n_servers`` — pull/push fan out per-shard and
        reassemble in request order.
      - dense tables live on one server each, placed by
        ``zlib.adler32(name) % n_servers`` (deterministic across processes,
        unlike Python's salted str hash; dense state is small next to
        sparse embeddings).
    ``push_*_async`` returns a future-like list; ``wait()`` drains every
    outstanding push — the reference's async push + barrier pattern.
    """

    def __init__(self, servers: Sequence[str]):
        if not servers:
            raise ValueError("need at least one server name")
        self.servers = list(servers)
        self.workers = [PsWorker(s) for s in self.servers]
        self._pending: List[object] = []

    # -- placement --
    def _dense_worker(self, name: str) -> PsWorker:
        import zlib

        return self.workers[zlib.adler32(name.encode()) % len(self.workers)]

    def _shard_ids(self, ids: Sequence[int]):
        """Group ids by owning server, remembering original positions."""
        n = len(self.workers)
        groups: Dict[int, List[int]] = {}
        pos: Dict[int, List[int]] = {}
        for i, fid in enumerate(ids):
            s = int(fid) % n
            groups.setdefault(s, []).append(int(fid))
            pos.setdefault(s, []).append(i)
        return groups, pos

    # -- tables --
    def create_dense_table(self, name, shape, **kw):
        return self._dense_worker(name).create_dense_table(name, shape, **kw)

    def create_sparse_table(self, name, emb_dim, **kw):
        # sparse tables exist on every shard
        return all(w.create_sparse_table(name, emb_dim, **kw)
                   for w in self.workers)

    # -- dense --
    def pull_dense(self, name) -> np.ndarray:
        return self._dense_worker(name).pull_dense(name)

    def push_dense(self, name, grad) -> bool:
        return self._dense_worker(name).push_dense(name, grad)

    # -- sparse (per-shard fan-out) --
    def pull_sparse(self, name, ids) -> np.ndarray:
        if len(ids) == 0:
            # preserve the single-server (0, emb_dim) contract
            return self.workers[0].pull_sparse(name, [])
        groups, pos = self._shard_ids(ids)
        futs = {s: rpc.rpc_async(self.servers[s], _dispatch,
                                 args=("pull_sparse", name, fids))
                for s, fids in groups.items()}
        out: Optional[np.ndarray] = None
        for s, fut in futs.items():
            rows = np.asarray(fut.result())
            if out is None:
                out = np.zeros((len(ids), rows.shape[-1]), rows.dtype)
            out[pos[s]] = rows
        return out

    def push_sparse(self, name, ids, grads) -> bool:
        futs = self.push_sparse_async(name, ids, grads)
        try:
            self._drain(futs)
        finally:
            # drained (or failed) futures must leave the barrier set either
            # way — a later wait() must not re-raise this call's error
            fset = set(map(id, futs))
            self._pending = [f for f in self._pending if id(f) not in fset]
        return True

    def push_sparse_async(self, name, ids, grads):
        """Fire the per-shard pushes without blocking; drain via wait()."""
        grads = np.asarray(grads)
        groups, pos = self._shard_ids(ids)
        futs = [rpc.rpc_async(self.servers[s], _dispatch,
                              args=("push_sparse", name, fids,
                                    grads[pos[s]]))
                for s, fids in groups.items()]
        self._pending.extend(futs)
        return futs

    @staticmethod
    def _drain(futs):
        """Await EVERY future even when some fail, then re-raise the first
        error — a barrier that abandons in-flight pushes on the error path
        would let the caller race still-mutating shards."""
        first_err = None
        for f in futs:
            try:
                f.result()
            except Exception as e:
                if first_err is None:
                    first_err = e
        if first_err is not None:
            raise first_err

    def wait(self):
        """Barrier for every outstanding async push (reference: the PS
        client's flush before pull/evaluation)."""
        pending, self._pending = self._pending, []
        self._drain(pending)

    def stat(self):
        return {s: w.stat() for s, w in zip(self.servers, self.workers)}
