"""paddle_tpu.distributed.ps — parameter-server training, host-side emulation.

Parity anchors: the reference's brpc PS stack
(/root/reference/paddle/fluid/distributed/ps/service/brpc_ps_server.h,
ps/table/memory_sparse_table.h dense/sparse tables with server-side
optimizers, python/paddle/distributed/ps/ glue).

Scope note (TPU-native): the reference's PS mode exists for CPU-cluster
trillion-parameter embedding models. On TPU pods the same workload is served
by sharded embedding tables over ICI (expert/embedding sharding in the SPMD
engine). This module provides a functional host-side PS — dense/sparse tables
with server-side SGD/Adagrad, push/pull over the RPC layer — so PS-paradigm
programs port and small-scale PS jobs run; it intentionally does not
reimplement brpc/heter-PS scale-out. Cf. SURVEY.md §2 #30/#31.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from . import _tables
from .. import rpc

__all__ = ["ParameterServer", "PsWorker", "DenseTable", "SparseTable",
           "run_server", "stop_server"]

DenseTable = _tables.DenseTable
SparseTable = _tables.SparseTable


class ParameterServer:
    """Holds tables; methods are invoked remotely via the rpc layer."""

    def __init__(self):
        self._tables: Dict[str, object] = {}
        self._lock = threading.Lock()

    # -- table management --
    def create_dense_table(self, name: str, shape, optimizer="sgd", lr=0.01,
                           initializer="zeros"):
        with self._lock:
            existing = self._tables.get(name)
            if existing is None:
                self._tables[name] = DenseTable(shape, optimizer, lr, initializer)
            elif (not isinstance(existing, DenseTable)
                  or list(existing.value.shape) != [int(s) for s in shape]
                  or existing.optimizer != optimizer
                  or existing.lr != float(lr)
                  or existing.initializer != initializer):
                raise ValueError(
                    f"dense table '{name}' already exists with a different "
                    f"config: {existing.stat()}")
        return True

    def create_sparse_table(self, name: str, emb_dim: int, optimizer="adagrad",
                            lr=0.01, init_range=0.01):
        with self._lock:
            existing = self._tables.get(name)
            if existing is None:
                self._tables[name] = SparseTable(emb_dim, optimizer, lr,
                                                 init_range)
            elif (not isinstance(existing, SparseTable)
                  or existing.emb_dim != int(emb_dim)
                  or existing.optimizer != optimizer
                  or existing.lr != float(lr)
                  or existing.init_range != float(init_range)):
                raise ValueError(
                    f"sparse table '{name}' already exists with a different "
                    f"config: {existing.stat()}")
        return True

    def _table(self, name):
        return self._tables[name]

    # -- dense --
    def pull_dense(self, name: str) -> np.ndarray:
        return self._table(name).pull()

    def push_dense(self, name: str, grad: np.ndarray):
        self._table(name).push(grad)
        return True

    # -- sparse --
    def pull_sparse(self, name: str, ids: Sequence[int]) -> np.ndarray:
        return self._table(name).pull(ids)

    def push_sparse(self, name: str, ids: Sequence[int], grads: np.ndarray):
        self._table(name).push(ids, grads)
        return True

    def stat(self):
        with self._lock:
            return {n: t.stat() for n, t in self._tables.items()}


_server: Dict[str, Optional[ParameterServer]] = {"ps": None}


def run_server() -> ParameterServer:
    """Make this rpc worker a parameter server (reference:
    fleet.init(role).run_server for the PSERVER role)."""
    if _server["ps"] is None:
        _server["ps"] = ParameterServer()
    return _server["ps"]


def stop_server():
    _server["ps"] = None


def _dispatch(method: str, *args):
    ps = _server["ps"]
    if ps is None:
        raise RuntimeError("this worker is not a parameter server "
                           "(call ps.run_server() there)")
    return getattr(ps, method)(*args)


class PsWorker:
    """Trainer-side handle: push/pull against a named server worker
    (reference: the fleet worker role using BrpcPsClient)."""

    def __init__(self, server_name: str = "ps0"):
        self.server = server_name

    def _call(self, method, *args):
        return rpc.rpc_sync(self.server, _dispatch, args=(method,) + args)

    def create_dense_table(self, name, shape, optimizer="sgd", lr=0.01,
                           initializer="zeros"):
        return self._call("create_dense_table", name, list(shape), optimizer,
                          lr, initializer)

    def create_sparse_table(self, name, emb_dim, optimizer="adagrad", lr=0.01,
                            init_range=0.01):
        return self._call("create_sparse_table", name, emb_dim, optimizer, lr,
                          init_range)

    def pull_dense(self, name) -> np.ndarray:
        return self._call("pull_dense", name)

    def push_dense(self, name, grad) -> bool:
        return self._call("push_dense", name, np.asarray(grad))

    def pull_sparse(self, name, ids) -> np.ndarray:
        return self._call("pull_sparse", name, [int(i) for i in ids])

    def push_sparse(self, name, ids, grads) -> bool:
        return self._call("push_sparse", name, [int(i) for i in ids],
                          np.asarray(grads))

    def stat(self):
        return self._call("stat")
