"""PS tables with server-side optimizers.

Reference: /root/reference/paddle/fluid/distributed/ps/table/ —
MemoryDenseTable (dense params + server-side SGD/Adam accessors) and
MemorySparseTable (lazy-materialized embedding rows with per-row optimizer
state, the "100B-feature" table). Host numpy here: PS tables live in host
RAM by design (that is the point of the paradigm).
"""

from __future__ import annotations

import threading
from typing import Dict, Sequence

import numpy as np


class DenseTable:
    def __init__(self, shape, optimizer="sgd", lr=0.01, initializer="zeros"):
        shape = tuple(int(s) for s in shape)
        if initializer == "zeros":
            self.value = np.zeros(shape, np.float32)
        else:
            rng = np.random.default_rng(0)
            self.value = rng.uniform(-0.01, 0.01, shape).astype(np.float32)
        self.optimizer = optimizer
        self.lr = float(lr)
        self.initializer = initializer
        self._acc = np.zeros(shape, np.float32)  # adagrad accumulator
        self._lock = threading.Lock()

    def pull(self) -> np.ndarray:
        with self._lock:
            return self.value.copy()

    def push(self, grad: np.ndarray):
        g = np.asarray(grad, np.float32)
        with self._lock:
            if self.optimizer == "adagrad":
                self._acc += g * g
                self.value -= self.lr * g / (np.sqrt(self._acc) + 1e-6)
            else:  # sgd
                self.value -= self.lr * g

    def stat(self):
        with self._lock:
            return {"kind": "dense", "shape": list(self.value.shape),
                    "optimizer": self.optimizer}


class SparseTable:
    """Lazy embedding rows keyed by int64 feature id (reference:
    memory_sparse_table.h — rows materialize on first touch)."""

    def __init__(self, emb_dim: int, optimizer="adagrad", lr=0.01,
                 init_range=0.01):
        self.emb_dim = int(emb_dim)
        self.optimizer = optimizer
        self.lr = float(lr)
        self.init_range = float(init_range)
        self._rows: Dict[int, np.ndarray] = {}
        self._acc: Dict[int, np.ndarray] = {}
        self._rng = np.random.default_rng(0)
        self._lock = threading.Lock()

    def _row(self, fid: int) -> np.ndarray:
        r = self._rows.get(fid)
        if r is None:
            r = self._rng.uniform(-self.init_range, self.init_range,
                                  self.emb_dim).astype(np.float32)
            self._rows[fid] = r
            self._acc[fid] = np.zeros(self.emb_dim, np.float32)
        return r

    def pull(self, ids: Sequence[int]) -> np.ndarray:
        if len(ids) == 0:
            return np.zeros((0, self.emb_dim), np.float32)
        with self._lock:
            return np.stack([self._row(int(i)) for i in ids])

    def push(self, ids: Sequence[int], grads: np.ndarray):
        g = np.asarray(grads, np.float32)
        with self._lock:
            for i, fid in enumerate(ids):
                fid = int(fid)
                row = self._row(fid)
                if self.optimizer == "adagrad":
                    self._acc[fid] += g[i] * g[i]
                    row -= self.lr * g[i] / (np.sqrt(self._acc[fid]) + 1e-6)
                else:
                    row -= self.lr * g[i]

    def stat(self):
        with self._lock:
            return {"kind": "sparse", "emb_dim": self.emb_dim,
                    "rows": len(self._rows), "optimizer": self.optimizer}
