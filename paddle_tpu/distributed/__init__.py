"""paddle_tpu.distributed (reference: python/paddle/distributed).

TPU-native distributed stack: mesh-axis groups + XLA collectives over ICI/DCN
replace ProcessGroupNCCL/TCPStore; GSPMD + NamedSharding replace DistTensor's SPMD
rules and reshard functions; fleet engines become shard_map programs.
"""

from . import checkpoint, fleet, ps, resilience, rpc, sharding, utils  # noqa: F401
from ..framework.numeric_guard import (  # noqa: F401
    BadBatchRecorder,
    GuardPolicy,
    NumericAnomalyError,
)
from .checkpoint import (  # noqa: F401
    CheckpointCorruptionError,
    StaleGenerationError,
    load_state_dict,
    save_state_dict,
    wait_async_save,
)
from .resilience import NumericWatchdog  # noqa: F401
from .resilience.lifecycle import CheckpointPublisher  # noqa: F401
from .auto_parallel import (  # noqa: F401
    Partial,
    Placement,
    ProcessMesh,
    Replicate,
    Shard,
    Strategy,
    dtensor_from_local,
    dtensor_to_local,
    get_mesh,
    reshard,
    set_mesh,
    shard_dataloader,
    shard_layer,
    shard_optimizer,
    shard_tensor,
    to_static,
    unshard_dtensor,
)
from .auto_parallel.api import ShardingStage1, ShardingStage2, ShardingStage3  # noqa: F401
from .communication.functional import (  # noqa: F401
    P2POp,
    all_gather,
    all_gather_into_tensor,
    all_gather_object,
    all_reduce,
    all_to_all,
    all_to_all_single,
    alltoall,
    alltoall_single,
    barrier,
    batch_isend_irecv,
    broadcast,
    broadcast_object_list,
    irecv,
    isend,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    scatter_object_list,
    send,
)
from .communication.group import Group, ReduceOp, destroy_process_group, get_group, new_group  # noqa: F401
from .communication.store import TCPStore  # noqa: F401
from .communication.watchdog import CommTaskManager, get_comm_task_manager  # noqa: F401
from .parallel import (  # noqa: F401
    DataParallel,
    ParallelEnv,
    get_rank,
    get_world_size,
    init_parallel_env,
    is_initialized,
    spawn,
)
