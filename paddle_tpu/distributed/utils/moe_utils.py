"""MoE dispatch utilities (reference: python/paddle/distributed/utils/moe_utils.py
``global_scatter``/``global_gather`` — NCCL alltoall of variable token counts).

TPU-native: inside shard_map with the ``ep`` axis bound these are
``lax.all_to_all``; in single-controller eager mode a jax.Array is already the
global tensor, so they reduce to static reshapes. The MoELayer does NOT need
them — its dense einsum dispatch lets GSPMD insert the all_to_all — they exist
for users porting reference code that calls them directly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ...core.tensor import Tensor


def _raw(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _axis_bound(axis_name) -> bool:
    try:
        lax.axis_index(axis_name)
        return True
    except NameError:
        return False
    except Exception:
        return False


def _axis_size(axis_name) -> int:
    """Static size of a bound mesh axis. ``lax.axis_size`` only exists on
    newer jax; on older versions ``lax.psum(1, axis)`` constant-folds to
    the same static int at trace time."""
    if hasattr(lax, "axis_size"):
        return int(lax.axis_size(axis_name))
    return int(lax.psum(1, axis_name))


def global_scatter(x, local_count=None, global_count=None, group=None,
                   axis_name: str = "ep"):
    """Send token slices to their expert's rank (reference moe_utils.py:32).

    x: [world * tokens_per_rank, d] laid out expert-major. Inside shard_map the
    leading dim is all_to_all'ed over ``axis_name``; eagerly it is a no-op
    (the array is already global).
    """
    x = _raw(x)
    if _axis_bound(axis_name):
        n = _axis_size(axis_name)
        parts = x.reshape((n, x.shape[0] // n) + x.shape[1:])
        return lax.all_to_all(parts, axis_name, 0, 0, tiled=False).reshape(x.shape)
    return x


def global_gather(x, local_count=None, global_count=None, group=None,
                  axis_name: str = "ep"):
    """Inverse of :func:`global_scatter` (reference moe_utils.py:151)."""
    return global_scatter(x, local_count, global_count, group, axis_name)
