"""paddle_tpu.distributed.rpc — user-level RPC.

Parity anchors: the reference's brpc-backed RpcAgent
(/root/reference/paddle/fluid/distributed/rpc/rpc_agent.h) and its Python API
(python/paddle/distributed/rpc/rpc.py: init_rpc / rpc_sync / rpc_async /
shutdown / get_worker_info).

TPU-native role: the collective fabric is XLA's; RPC serves the *control
plane* — parameter-server emulation (distributed/ps), custom coordination,
evaluation services. Implementation: one threaded TCP server per worker
executing pickled callables, with worker discovery through the TCPStore
rendezvous (communication/store.py), replacing brpc + etcd.

Trust model: pickle over job-internal sockets — same trust boundary as the
reference's brpc protobuf channel (any rank can already execute code on any
other via the training script itself).
"""

from __future__ import annotations

import concurrent.futures
import os
import pickle
import socket
import socketserver
import struct
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from ..communication.store import TCPStore
from ..resilience import faults as _faults
from ..resilience.retry import RetryPolicy, retry_call

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_worker_info", "get_all_worker_infos", "WorkerInfo"]

# connection establishment is retried (a peer mid-restart, an injected
# fault); the request/response exchange itself is NOT — rpc calls are not
# idempotent in general (ps push applies gradients), so a post-send failure
# must surface to the caller rather than silently re-execute.
_CONNECT_RETRY = RetryPolicy(max_attempts=4, base_delay=0.05, max_delay=1.0)


@dataclass
class WorkerInfo:
    name: str
    rank: int
    ip: str
    port: int


_state: Dict[str, Any] = {"agent": None}


def _send_msg(sock: socket.socket, payload: bytes):
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_msg(sock: socket.socket) -> bytes:
    hdr = b""
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            raise ConnectionError("rpc peer closed")
        hdr += chunk
    (n,) = struct.unpack("<Q", hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("rpc peer closed")
        buf += chunk
    return bytes(buf)


class _RpcHandler(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            payload = _recv_msg(self.request)
            fn, args, kwargs = pickle.loads(payload)
            try:
                result = (True, fn(*args, **kwargs))
            except Exception as e:  # error travels back to the caller
                result = (False, e)
            try:
                payload = pickle.dumps(result)
            except Exception as e:  # unpicklable result/exception
                payload = pickle.dumps(
                    (False, RuntimeError(
                        f"rpc result not picklable: {e!r} "
                        f"(original: {result[1]!r})" if not result[0]
                        else f"rpc result not picklable: {e!r}")))
            _send_msg(self.request, payload)
        except ConnectionError:
            pass


class _ThreadedServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class RpcAgent:
    """Per-process agent: a serving thread + client connections to peers."""

    def __init__(self, name: str, rank: int, world_size: int, store: TCPStore):
        self.name = name
        self.rank = rank
        self.world_size = world_size
        self._store = store
        self._server = _ThreadedServer(("0.0.0.0", 0), _RpcHandler)
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        self._pool = concurrent.futures.ThreadPoolExecutor(max_workers=16)

        # advertise the address peers can actually reach: the local interface
        # that routes toward the store master (PADDLE_LOCAL_IP overrides)
        ip = os.environ.get("PADDLE_LOCAL_IP")
        if ip is None:
            try:
                with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as probe:
                    probe.connect((store.host, max(store.port, 1)))
                    ip = probe.getsockname()[0]
            except OSError:
                ip = "127.0.0.1"
        info = WorkerInfo(name, rank, ip, self._port)
        store.set(f"rpc/worker/{rank}", pickle.dumps(info))
        store.set(f"rpc/name/{name}", pickle.dumps(info))
        store.add("rpc/ready", 1)
        store.wait_ge("rpc/ready", world_size)
        self._workers: Dict[str, WorkerInfo] = {}
        for r in range(world_size):
            w = pickle.loads(store.get(f"rpc/worker/{r}"))
            self._workers[w.name] = w

    def worker(self, name: str) -> WorkerInfo:
        if name not in self._workers:
            raise KeyError(f"unknown rpc worker '{name}' "
                           f"(known: {sorted(self._workers)})")
        return self._workers[name]

    def call(self, to: str, fn: Callable, args=(), kwargs=None,
             timeout: Optional[float] = None):
        w = self.worker(to)

        def connect():
            _faults.maybe_inject("rpc.connect", to)
            return socket.create_connection((w.ip, w.port), timeout=timeout)

        with retry_call(connect, policy=_CONNECT_RETRY,
                        what=f"rpc.connect({to})") as s:
            _send_msg(s, pickle.dumps((fn, tuple(args), dict(kwargs or {}))))
            s.settimeout(timeout)
            ok, result = pickle.loads(_recv_msg(s))
        if not ok:
            raise result
        return result

    def call_async(self, to: str, fn, args=(), kwargs=None, timeout=None):
        return self._pool.submit(self.call, to, fn, args, kwargs, timeout)

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        self._pool.shutdown(wait=False)


def init_rpc(name: str, rank: Optional[int] = None,
             world_size: Optional[int] = None,
             master_endpoint: Optional[str] = None,
             store: Optional[TCPStore] = None) -> RpcAgent:
    """Start this process's RPC agent and rendezvous with peers
    (reference: rpc.py init_rpc; env fallbacks mirror PADDLE_TRAINER_*)."""
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) if rank is None else rank
    world_size = (int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
                  if world_size is None else world_size)
    existing = _state["agent"]
    if existing is not None:
        if (existing.name, existing.rank, existing.world_size) != (
                name, rank, world_size):
            raise RuntimeError(
                f"rpc already initialized as ({existing.name}, rank "
                f"{existing.rank}, world {existing.world_size}); call "
                f"shutdown() before re-initializing with different parameters")
        return existing
    if store is None:
        ep = master_endpoint or os.environ.get("PADDLE_MASTER_ENDPOINT",
                                               "127.0.0.1:29600")
        host, port = ep.rsplit(":", 1)
        store = TCPStore(host, int(port), is_master=(rank == 0),
                         world_size=world_size)
    agent = RpcAgent(name, rank, world_size, store)
    _state["agent"] = agent
    return agent


def _agent() -> RpcAgent:
    if _state["agent"] is None:
        raise RuntimeError("call paddle.distributed.rpc.init_rpc first")
    return _state["agent"]


def rpc_sync(to: str, fn: Callable, args=(), kwargs=None, timeout=None):
    return _agent().call(to, fn, args, kwargs, timeout)


def rpc_async(to: str, fn: Callable, args=(), kwargs=None, timeout=None):
    return _agent().call_async(to, fn, args, kwargs, timeout)


def get_worker_info(name: Optional[str] = None) -> WorkerInfo:
    a = _agent()
    if name is None:
        return a._workers[a.name]
    return a.worker(name)


def get_all_worker_infos() -> List[WorkerInfo]:
    return sorted(_agent()._workers.values(), key=lambda w: w.rank)


def shutdown():
    a = _state["agent"]
    if a is not None:
        a.stop()
        _state["agent"] = None
