"""Generation-fenced LATEST resume pointer.

``LATEST`` names the newest durable checkpoint step. Before this module it
was a bare integer, which left two seams open (docs/RESILIENCE.md
"Checkpoint lifecycle"):

1. **Stale writer** — after an elastic shrink (or an operator restart) the
   OLD trainer process may still be alive and mid-save. Its late
   ``commit()`` would clobber the new trainer's pointer with an older
   checkpoint, silently rewinding the job.
2. **Torn async flush** — the pointer must only ever move after
   ``wait_async_save`` proves every shard durable; the fence makes that
   ordering an invariant of the commit primitive itself, not a property of
   one caller.

The fix is a monotonic **generation token** carried inside LATEST
(``"<step> <generation>"``). Every writer claims a generation strictly
above the committed one (:func:`claim_generation`); :func:`commit_latest`
refuses — typed :class:`StaleGenerationError`, PT-CKPT-005 — any commit
whose token is below the generation already on disk. The file itself moves
via the same tempfile + ``os.replace`` as every shard, so the pointer is
atomic: readers see the old (step, generation) pair or the new one, never
a torn mix.

Back-compat: a bare-integer LATEST from an older run parses as generation
0, so any fenced writer (generation >= 1) supersedes it.
"""

from __future__ import annotations

import os
import threading
from typing import Optional, Tuple

from .integrity import atomic_write_bytes

__all__ = ["LATEST_FILE", "StaleGenerationError", "read_latest",
           "latest_generation", "claim_generation", "commit_latest"]

LATEST_FILE = "LATEST"

# read-check-replace below must be one critical section per process: the
# async-save committer and a concurrent publisher share this path
# (PT-RACE discipline, tools/lint_concurrency.py)
_LATEST_LOCK = threading.Lock()


class StaleGenerationError(RuntimeError):
    """A writer holding an outdated generation token tried to move LATEST.

    Attributes: ``path`` (checkpoint root), ``committed`` (generation on
    disk), ``attempted`` (the stale writer's token). Code PT-CKPT-005.
    """

    code = "PT-CKPT-005"

    def __init__(self, path: str, committed: int, attempted: int):
        self.path = path
        self.committed = committed
        self.attempted = attempted
        super().__init__(
            f"PT-CKPT-005: stale checkpoint writer fenced in {path}: "
            f"generation {attempted} < committed generation {committed} "
            f"(a newer trainer/publisher owns this directory)")


def read_latest(ckpt_dir: str) -> Optional[Tuple[int, int]]:
    """Parse LATEST into ``(step, generation)``; ``None`` when missing or
    unparsable. Legacy bare-int pointers read as generation 0."""
    try:
        with open(os.path.join(ckpt_dir, LATEST_FILE)) as f:
            fields = f.read().split()
        step = int(fields[0])
        gen = int(fields[1]) if len(fields) > 1 else 0
        return step, gen
    except (OSError, ValueError, IndexError):
        return None


def latest_generation(ckpt_dir: str) -> int:
    """The committed generation (0 when no fenced LATEST exists yet)."""
    rec = read_latest(ckpt_dir)
    return rec[1] if rec is not None else 0


def claim_generation(ckpt_dir: str) -> int:
    """Claim a generation token strictly above everything committed — what
    a new trainer (or publisher taking ownership) calls once at startup.
    Any writer still holding an older token is fenced from then on."""
    with _LATEST_LOCK:
        return latest_generation(ckpt_dir) + 1


def commit_latest(ckpt_dir: str, step: int, generation: int) -> None:
    """Atomically move the resume pointer to ``step`` under ``generation``.

    Raises :class:`StaleGenerationError` when the on-disk generation is
    already above ``generation`` — the caller is a zombie writer and must
    not publish. Equal generations commit freely (the same writer moves
    its own pointer forward across saves)."""
    with _LATEST_LOCK:
        committed = latest_generation(ckpt_dir)
        if int(generation) < committed:
            raise StaleGenerationError(ckpt_dir, committed, int(generation))
        atomic_write_bytes(os.path.join(ckpt_dir, LATEST_FILE),
                           f"{int(step)} {int(generation)}".encode())
