"""Checkpoint integrity — atomic shard writes, checksums, typed corruption.

The save path records per-shard digests (crc32 + sha256 + size) in
``0.metadata``; the load path verifies every shard file before any chunk is
read and raises :class:`CheckpointCorruptionError` *naming the bad shard*
instead of surfacing a BadZipFile (or silently wrong weights) from deep
inside ``np.load``. When a ``<shard>.replica`` copy exists and verifies,
the loader recovers from it transparently.

Diagnostic codes (docs/RESILIENCE.md):

- ``PT-CKPT-001`` — shard digest mismatch (bit-flip / partial overwrite)
- ``PT-CKPT-002`` — shard truncated (size mismatch)
- ``PT-CKPT-003`` — shard file referenced by the metadata is missing
  (torn save)
- ``PT-CKPT-004`` — shard unreadable / undecodable

All writes go through :func:`atomic_write_bytes` (same-directory tempfile +
``os.replace``), so a crash mid-write leaves either the old file or the new
one — never a torn shard.
"""

from __future__ import annotations

import hashlib
import os
import tempfile
import zlib
from typing import Dict, Optional

__all__ = ["CheckpointCorruptionError", "atomic_write_bytes",
           "file_digests", "verify_shard_bytes", "verify_shard_file",
           "REPLICA_SUFFIX"]

REPLICA_SUFFIX = ".replica"


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint shard failed integrity verification.

    Attributes: ``code`` (PT-CKPT-xxx), ``path`` (checkpoint dir),
    ``shard`` (the bad file's name), ``reason``.
    """

    def __init__(self, code: str, path: str, shard: str, reason: str):
        self.code = code
        self.path = path
        self.shard = shard
        self.reason = reason
        super().__init__(
            f"{code}: checkpoint shard '{shard}' in {path}: {reason}")


def file_digests(data: bytes) -> Dict[str, object]:
    """The integrity record stored per shard file in ``0.metadata``."""
    return {
        "size": len(data),
        "crc32": zlib.crc32(data) & 0xFFFFFFFF,
        "sha256": hashlib.sha256(data).hexdigest(),
    }


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Write-then-rename so readers never observe a partial file."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(prefix=".pt_tmp_", dir=d)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _check_digests(size: int, crc: int, sha_hex: str,
                   record: Optional[Dict], path: str, shard: str) -> None:
    if record is None:
        # pre-integrity checkpoint: verifies vacuously, stays loadable
        return
    want_size = record.get("size")
    if want_size is not None and size != int(want_size):
        raise CheckpointCorruptionError(
            "PT-CKPT-002", path, shard,
            f"truncated: {size} bytes on disk, {want_size} recorded")
    want_crc = record.get("crc32")
    if want_crc is not None and crc != int(want_crc):
        raise CheckpointCorruptionError(
            "PT-CKPT-001", path, shard,
            f"crc32 mismatch: {crc:#010x} on disk, "
            f"{int(want_crc):#010x} recorded")
    want_sha = record.get("sha256")
    if want_sha is not None and sha_hex != want_sha:
        raise CheckpointCorruptionError(
            "PT-CKPT-001", path, shard, "sha256 mismatch")


def verify_shard_bytes(data: bytes, record: Optional[Dict], path: str,
                       shard: str) -> None:
    """Check in-memory ``data`` against its recorded digests; raise a
    typed, named corruption error on mismatch."""
    _check_digests(len(data), zlib.crc32(data) & 0xFFFFFFFF,
                   hashlib.sha256(data).hexdigest(), record, path, shard)


def verify_shard_file(fpath: str, record: Optional[Dict], path: str,
                      shard: str, chunk_size: int = 1 << 20) -> None:
    """Digest-check a shard ON DISK in fixed-size chunks — peak memory is
    one chunk, not the whole (multi-GB) shard. FileNotFoundError
    propagates; digest mismatches raise the same PT-CKPT errors as the
    bytes variant."""
    size, crc, sha = 0, 0, hashlib.sha256()
    with open(fpath, "rb") as f:
        while True:
            block = f.read(chunk_size)
            if not block:
                break
            size += len(block)
            crc = zlib.crc32(block, crc)
            sha.update(block)
    _check_digests(size, crc & 0xFFFFFFFF, sha.hexdigest(), record, path,
                   shard)
