"""Distributed checkpoint load with reshard-on-load (reference:
/root/reference/python/paddle/distributed/checkpoint/load_state_dict.py —
computes the intersection of saved chunks and needed local shards, reads only
overlapping slices, and communicates what isn't local).

TPU-native: the target placement is the destination state_dict's NamedSharding
(any mesh/degree — that IS reshard-on-load). For every target tensor each
process assembles the pieces of ITS addressable shards from the overlapping
saved chunks, then builds the global jax.Array via
``jax.make_array_from_single_device_arrays``.

Integrity (docs/RESILIENCE.md): every shard file is verified against the
digests recorded in ``0.metadata`` *before* any chunk is read — corruption
raises :class:`CheckpointCorruptionError` naming the bad shard (PT-CKPT
codes) instead of a BadZipFile from inside ``np.load`` or silently wrong
weights. A verifying ``<shard>.replica`` copy, when present, recovers the
load transparently. ``verify=False`` opts out (the fault drill uses it to
demonstrate why you shouldn't).
"""

from __future__ import annotations

import os
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from .integrity import (REPLICA_SUFFIX, CheckpointCorruptionError,
                        verify_shard_file)
from .metadata import Metadata, index_to_offsets
from .save_state_dict import _flatten_state_dict, wait_async_save


class _ChunkReader:
    """Lazily opens the .npz data files referenced by the metadata; caches
    decompressed members (NpzFile decompresses on every __getitem__).
    Verifies each file's digests (and falls back to its replica) on first
    open."""

    def __init__(self, path: str, files: Dict = None, verify: bool = True):
        self.path = path
        self.files = files or {}
        self.verify = verify
        self._files = {}
        self._members = {}

    def _verified_path(self, fname: str) -> str:
        """Digest-check ``fname`` (chunked — peak memory one block, not the
        shard) and return the on-disk path to read — the primary, or its
        verifying replica when the primary is corrupt."""
        primary = os.path.join(self.path, fname)
        if not self.verify:
            # no integrity machinery at all: raw IO/decoder errors propagate
            # — the fault drill contrasts this against verified loads
            return primary
        rec = self.files.get(fname)
        try:
            verify_shard_file(primary, rec, self.path, fname)
            return primary
        except FileNotFoundError:
            primary_err = CheckpointCorruptionError(
                "PT-CKPT-003", self.path, fname,
                "data file missing (torn save?)")
        except CheckpointCorruptionError as e:
            primary_err = e
        # primary bad: a verifying replica recovers the load. The replica
        # is held to the SAME digest record as the primary — a corrupt
        # fallback must never load silently.
        try:
            verify_shard_file(primary + REPLICA_SUFFIX, rec, self.path,
                              fname + REPLICA_SUFFIX)
            return primary + REPLICA_SUFFIX
        except FileNotFoundError:
            if not os.path.exists(primary + REPLICA_SUFFIX):
                # no replica was ever written: the primary's error stands
                raise primary_err from None
            replica_reason = "replica vanished mid-verify"
        except CheckpointCorruptionError as re_err:
            replica_reason = f"{re_err.code}: {re_err.reason}"
        # BOTH copies failed: name each copy and its failure, so the
        # operator knows this checkpoint is unrecoverable (not merely that
        # the primary was bad and a replica might have saved it)
        raise CheckpointCorruptionError(
            primary_err.code, self.path, fname,
            f"primary and replica both failed verification — "
            f"primary: {primary_err.reason}; "
            f"replica ({fname + REPLICA_SUFFIX}): {replica_reason}"
        ) from None

    def _open(self, fname: str):
        if fname not in self._files:
            # np.load on the verified PATH, not the verification bytes: the
            # zip is then read lazily per member, so peak memory stays at
            # the decompressed chunks actually requested
            path = self._verified_path(fname)
            if not self.verify:
                self._files[fname] = np.load(path)
                return self._files[fname]
            try:
                self._files[fname] = np.load(path)
            except Exception as e:
                raise CheckpointCorruptionError(
                    "PT-CKPT-004", self.path, fname,
                    f"undecodable shard container: {e!r}") from e
        return self._files[fname]

    def read(self, rec):
        ck = (rec.file, rec.key)
        if ck not in self._members:
            self._members[ck] = self._open(rec.file)[rec.key]
        return self._members[ck]


def _assemble_slice(meta, reader, name, offsets, lengths, dtype):
    """Gather the [offsets, offsets+lengths) window of tensor `name`."""
    tm = meta.tensors[name]
    out = np.zeros(lengths, dtype=np.uint16 if dtype == jnp.bfloat16 else dtype)
    covered = np.zeros(lengths, dtype=bool) if out.ndim else np.zeros((), bool)
    for rec in tm.chunks:
        # overlap of [rec.offsets, +rec.lengths) with the wanted window
        src_sel, dst_sel = [], []
        overlap = True
        for ro, rl, wo, wl in zip(rec.offsets, rec.lengths, offsets, lengths):
            lo = max(ro, wo)
            hi = min(ro + rl, wo + wl)
            if hi <= lo:
                overlap = False
                break
            src_sel.append(slice(lo - ro, hi - ro))
            dst_sel.append(slice(lo - wo, hi - wo))
        if not overlap:
            continue
        data = reader.read(rec)
        out[tuple(dst_sel)] = data[tuple(src_sel)]
        if covered.ndim:
            covered[tuple(dst_sel)] = True
        else:
            covered = np.asarray(True)
    if not np.all(covered):
        raise ValueError(f"checkpoint chunks do not cover tensor {name!r} "
                         f"window offsets={offsets} lengths={lengths}")
    if dtype == jnp.bfloat16:
        return out.view(jnp.bfloat16)
    return out


def load_state_dict(state_dict: Dict, path: str, process_group=None,
                    coordinator_rank: int = 0, unique_id=None,
                    offload: bool = False, verify: bool = True) -> None:
    """In-place load into ``state_dict`` (reference semantics): every tensor is
    filled with checkpoint data laid out per its CURRENT sharding."""
    wait_async_save(path)               # a save in flight here must land first
    meta_path = os.path.join(path, "0.metadata")
    with open(meta_path) as f:
        meta = Metadata.from_json(f.read())
    reader = _ChunkReader(path, files=meta.files, verify=verify)
    for name, container, key, v in _flatten_with_refs(state_dict):
        if name not in meta.tensors:
            raise KeyError(f"tensor {name!r} not found in checkpoint {path}")
        tm = meta.tensors[name]
        arr = v._data if isinstance(v, Tensor) else v
        dtype = jnp.dtype(tm.dtype)
        if isinstance(arr, jax.Array) and len(arr.shape) == len(tm.global_shape):
            # reshard-on-load: assemble exactly this process's shards under the
            # DESTINATION sharding, whatever mesh/degree it uses
            sharding = arr.sharding
            pieces = []
            block_cache = {}  # replicated targets: assemble each window once
            for shard in arr.addressable_shards:
                offsets, lengths = index_to_offsets(shard.index, arr.shape)
                wk = (tuple(offsets), tuple(lengths))
                if wk not in block_cache:
                    block_cache[wk] = jnp.asarray(_assemble_slice(
                        meta, reader, name, offsets, lengths, dtype))
                pieces.append(jax.device_put(block_cache[wk], shard.device))
            new = jax.make_array_from_single_device_arrays(
                tuple(tm.global_shape), sharding, pieces)
        else:
            shape = tuple(tm.global_shape)
            full = _assemble_slice(meta, reader, name, [0] * len(shape),
                                   list(shape), dtype)
            new = jnp.asarray(full)
        if isinstance(v, Tensor):
            new = new.astype(v.dtype) if v._data is not None else new
            v._data = new
        else:
            container[key] = new


def _flatten_with_refs(state_dict, prefix=""):
    """Yield (flat_name, container, key, value) for in-place replacement."""
    for k, v in state_dict.items():
        name = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            yield from _flatten_with_refs(v, name)
        else:
            yield name, state_dict, k, v


def get_state_dict_shapes(state_dict):
    """Debug helper mirroring reference utils — {name: shape}."""
    return {k: list(np.shape(v._data if isinstance(v, Tensor) else v))
            for k, v in _flatten_state_dict(state_dict).items()}
