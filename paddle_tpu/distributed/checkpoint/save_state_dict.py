"""Distributed checkpoint save (reference:
/root/reference/python/paddle/distributed/checkpoint/save_state_dict.py:145 —
each rank writes its local shards to ``<rank>_0.distcp`` plus a coordinator-
written ``0.metadata`` of global shapes/offsets).

TPU-native: shards are ``jax.Array.addressable_shards`` — on multi-host each
process saves exactly the chunks it owns (deduped by replica id) to its own
``<process_index>_0.distcp`` (an .npz); process 0 writes ``0.metadata`` after a
metadata all-gather via jax.experimental.multihost_utils when running
multi-process, or directly in single-controller mode.
"""

from __future__ import annotations

import os
from typing import Dict

import jax
import numpy as np

from ...core.tensor import Tensor
from .metadata import ChunkRecord, Metadata, TensorMetadata, index_to_offsets


def _raw(v):
    if isinstance(v, Tensor):
        return v._data
    return v


def _flatten_state_dict(state_dict, prefix=""):
    flat = {}
    for k, v in state_dict.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            flat.update(_flatten_state_dict(v, key))
        else:
            flat[key] = v
    return flat


def save_state_dict(state_dict: Dict, path: str, process_group=None,
                    coordinator_rank: int = 0, unique_id=None,
                    async_save: bool = False) -> None:
    """Save a (possibly sharded) state_dict to ``path``.

    Every value may be a Tensor/jax.Array with any NamedSharding; only locally
    addressable, first-replica chunks are written by this process, so the total
    bytes across hosts equal one copy of the model.
    """
    flat = _flatten_state_dict(state_dict)
    proc = jax.process_index()
    os.makedirs(path, exist_ok=True)
    fname = f"{proc}_0.distcp"
    chunks_out = {}
    meta_tensors: Dict[str, TensorMetadata] = {}
    for name, v in flat.items():
        arr = _raw(v)
        if arr is None:
            continue
        if not isinstance(arr, jax.Array):
            arr = np.asarray(arr)
            key = f"{name}|full"
            chunks_out[key] = arr
            meta_tensors[name] = TensorMetadata(
                global_shape=list(arr.shape), dtype=str(arr.dtype),
                chunks=[ChunkRecord(offsets=[0] * arr.ndim,
                                    lengths=list(arr.shape), file=fname, key=key)])
            continue
        records = []
        seen = set()
        for shard in arr.addressable_shards:
            if shard.replica_id != 0:
                continue  # only one replica writes a given chunk
            offsets, lengths = index_to_offsets(shard.index, arr.shape)
            tag = tuple(offsets)
            if tag in seen:
                continue
            seen.add(tag)
            key = f"{name}|{','.join(map(str, offsets)) or 'scalar'}"
            data = np.asarray(shard.data)
            if data.dtype == jax.numpy.bfloat16:
                chunks_out[key] = data.view(np.uint16)
            else:
                chunks_out[key] = data
            records.append(ChunkRecord(offsets=offsets, lengths=lengths,
                                       file=fname, key=key))
        meta_tensors[name] = TensorMetadata(
            global_shape=list(arr.shape), dtype=str(arr.dtype), chunks=records)
    with open(os.path.join(path, fname), "wb") as f:
        np.savez(f, **chunks_out)

    if jax.process_count() > 1:
        # shared-FS protocol (like the reference): every process writes a
        # partial metadata file, barrier, coordinator merges them
        with open(os.path.join(path, f"{proc}.metadata.part"), "w") as f:
            f.write(Metadata(meta_tensors).to_json())
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("ckpt_meta_parts")
        if proc == coordinator_rank:
            merged: Dict[str, TensorMetadata] = {}
            for p in range(jax.process_count()):
                with open(os.path.join(path, f"{p}.metadata.part")) as f:
                    m = Metadata.from_json(f.read())
                for name, tm in m.tensors.items():
                    if name in merged:
                        merged[name].chunks.extend(tm.chunks)
                    else:
                        merged[name] = tm
            with open(os.path.join(path, "0.metadata"), "w") as f:
                f.write(Metadata(merged).to_json())
        multihost_utils.sync_global_devices("ckpt_meta_merged")
    else:
        with open(os.path.join(path, "0.metadata"), "w") as f:
            f.write(Metadata(meta_tensors).to_json())
