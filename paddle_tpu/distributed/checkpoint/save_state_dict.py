"""Distributed checkpoint save (reference:
/root/reference/python/paddle/distributed/checkpoint/save_state_dict.py:145 —
each rank writes its local shards to ``<rank>_0.distcp`` plus a coordinator-
written ``0.metadata`` of global shapes/offsets).

TPU-native: shards are ``jax.Array.addressable_shards`` — on multi-host each
process saves exactly the chunks it owns (deduped by replica id) to its own
``<process_index>_0.distcp`` (an .npz); process 0 writes ``0.metadata`` after a
metadata all-gather via jax.experimental.multihost_utils when running
multi-process, or directly in single-controller mode.

Integrity (docs/RESILIENCE.md): shard bytes are serialized in memory, their
digests (size/crc32/sha256) recorded in ``0.metadata``, and every file —
shards and metadata alike — lands via tempfile + ``os.replace``, so a crash
mid-save can never leave a torn file, and the metadata (written last) is the
checkpoint's commit record. ``replica=True`` writes a ``.replica`` copy of
each shard for load-time recovery from single-copy corruption.
``async_save=True`` snapshots the device arrays synchronously and moves the
file IO to a background thread; ``wait_async_save()`` (also run at
interpreter exit, and before any new save to the same path) flushes it.
Fault site ``checkpoint.shard`` corrupts the primary shard bytes after
digest recording — how the corruption drills are seeded.
"""

from __future__ import annotations

import atexit
import io
import os
import threading
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from ...core.tensor import Tensor
from ..resilience import faults as _faults
from .integrity import REPLICA_SUFFIX, atomic_write_bytes, file_digests
from .metadata import ChunkRecord, Metadata, TensorMetadata, index_to_offsets

__all__ = ["save_state_dict", "wait_async_save"]


def _raw(v):
    if isinstance(v, Tensor):
        return v._data
    return v


def _flatten_state_dict(state_dict, prefix=""):
    flat = {}
    for k, v in state_dict.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            flat.update(_flatten_state_dict(v, key))
        else:
            flat[key] = v
    return flat


# in-flight async saves: (path, thread, error-holder)
_ASYNC: List[Tuple[str, threading.Thread, list]] = []
_ASYNC_LOCK = threading.Lock()


def wait_async_save(path: Optional[str] = None) -> None:
    """Block until pending ``async_save`` writes (to ``path``, or all of
    them) are durable; re-raises the first writer error. Registered at
    interpreter exit so a save in flight at shutdown still completes —
    without this flush an elastic restart could resume from a checkpoint
    whose metadata never landed."""
    with _ASYNC_LOCK:
        mine = [rec for rec in _ASYNC
                if path is None or rec[0] == os.path.abspath(path)]
        for rec in mine:
            _ASYNC.remove(rec)
    first_err = None
    for _, thread, err in mine:
        thread.join()
        if err and first_err is None:
            first_err = err[0]
    if first_err is not None:
        raise first_err


atexit.register(wait_async_save)


def _write_files(path: str, fname: str, blob: bytes, replica: bool) -> None:
    # the fault site corrupts the PRIMARY copy only — digests were recorded
    # from the clean bytes, so load-time verification must catch this
    primary = _faults.corrupt("checkpoint.shard", fname, blob)
    atomic_write_bytes(os.path.join(path, fname), primary)
    if replica:
        atomic_write_bytes(os.path.join(path, fname + REPLICA_SUFFIX), blob)


def save_state_dict(state_dict: Dict, path: str, process_group=None,
                    coordinator_rank: int = 0, unique_id=None,
                    async_save: bool = False, replica: bool = False) -> None:
    """Save a (possibly sharded) state_dict to ``path``.

    Every value may be a Tensor/jax.Array with any NamedSharding; only locally
    addressable, first-replica chunks are written by this process, so the total
    bytes across hosts equal one copy of the model.
    """
    wait_async_save(path)               # never interleave saves to one dir
    flat = _flatten_state_dict(state_dict)
    proc = jax.process_index()
    os.makedirs(path, exist_ok=True)
    fname = f"{proc}_0.distcp"
    chunks_out = {}
    meta_tensors: Dict[str, TensorMetadata] = {}
    for name, v in flat.items():
        arr = _raw(v)
        if arr is None:
            continue
        if not isinstance(arr, jax.Array):
            arr = np.asarray(arr)
            key = f"{name}|full"
            chunks_out[key] = arr
            meta_tensors[name] = TensorMetadata(
                global_shape=list(arr.shape), dtype=str(arr.dtype),
                chunks=[ChunkRecord(offsets=[0] * arr.ndim,
                                    lengths=list(arr.shape), file=fname, key=key)])
            continue
        records = []
        seen = set()
        for shard in arr.addressable_shards:
            if shard.replica_id != 0:
                continue  # only one replica writes a given chunk
            offsets, lengths = index_to_offsets(shard.index, arr.shape)
            tag = tuple(offsets)
            if tag in seen:
                continue
            seen.add(tag)
            key = f"{name}|{','.join(map(str, offsets)) or 'scalar'}"
            data = np.asarray(shard.data)
            if data.dtype == jax.numpy.bfloat16:
                chunks_out[key] = data.view(np.uint16)
            else:
                chunks_out[key] = data
            records.append(ChunkRecord(offsets=offsets, lengths=lengths,
                                       file=fname, key=key))
        meta_tensors[name] = TensorMetadata(
            global_shape=list(arr.shape), dtype=str(arr.dtype), chunks=records)
    # serialize in memory: digests come from the exact bytes that hit disk,
    # and async mode ships bytes (not live device arrays) to the writer
    buf = io.BytesIO()
    np.savez(buf, **chunks_out)
    blob = buf.getvalue()
    digests = {fname: file_digests(blob)}

    if jax.process_count() > 1:
        # shared-FS protocol (like the reference): every process writes a
        # partial metadata file, barrier, coordinator merges them.
        # async_save is demoted to sync here — the two sync_global_devices
        # fences below ARE the durability barrier for the job.
        _write_files(path, fname, blob, replica)
        atomic_write_bytes(
            os.path.join(path, f"{proc}.metadata.part"),
            Metadata(meta_tensors, files=digests).to_json().encode())
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("ckpt_meta_parts")
        if proc == coordinator_rank:
            merged: Dict[str, TensorMetadata] = {}
            merged_files: Dict[str, Dict] = {}
            for p in range(jax.process_count()):
                with open(os.path.join(path, f"{p}.metadata.part")) as f:
                    m = Metadata.from_json(f.read())
                merged_files.update(m.files or {})
                for name, tm in m.tensors.items():
                    if name in merged:
                        merged[name].chunks.extend(tm.chunks)
                    else:
                        merged[name] = tm
            atomic_write_bytes(
                os.path.join(path, "0.metadata"),
                Metadata(merged, files=merged_files).to_json().encode())
        multihost_utils.sync_global_devices("ckpt_meta_merged")
        return

    meta_blob = Metadata(meta_tensors, files=digests).to_json().encode()

    def write():
        _write_files(path, fname, blob, replica)
        # metadata last: its (atomic) appearance commits the checkpoint
        atomic_write_bytes(os.path.join(path, "0.metadata"), meta_blob)

    if not async_save:
        write()
        return
    err: list = []

    def runner():
        try:
            write()
        except BaseException as e:  # surfaced by wait_async_save
            err.append(e)

    thread = threading.Thread(target=runner, daemon=False,
                              name=f"pt-ckpt-save:{os.path.basename(path)}")
    with _ASYNC_LOCK:
        # publish + start under ONE critical section: a concurrent
        # wait_async_save taking the lock between them would pop the record
        # and join() a never-started thread (RuntimeError) — found by the
        # PT-RACE triage sweep; regression:
        # tests/test_resilience.py::test_async_save_starts_inside_lock
        _ASYNC.append((os.path.abspath(path), thread, err))
        thread.start()
