"""paddle.distributed.checkpoint parity — sharded save/load with
reshard-on-load (reference: python/paddle/distributed/checkpoint/)."""

from .load_state_dict import get_state_dict_shapes, load_state_dict  # noqa: F401
from .metadata import ChunkRecord, Metadata, TensorMetadata  # noqa: F401
from .save_state_dict import save_state_dict  # noqa: F401
