"""paddle.distributed.checkpoint parity — sharded save/load with
reshard-on-load (reference: python/paddle/distributed/checkpoint/), plus
integrity: atomic shard writes, per-shard checksums verified at load
(CheckpointCorruptionError names the bad shard), replica recovery, and
async saves flushed by wait_async_save, and a generation-fenced LATEST
resume pointer so stale writers can never rewind a job (latest.py,
docs/RESILIENCE.md)."""

from .integrity import CheckpointCorruptionError  # noqa: F401
from .latest import (StaleGenerationError, claim_generation,  # noqa: F401
                     commit_latest, read_latest)
from .load_state_dict import get_state_dict_shapes, load_state_dict  # noqa: F401
from .metadata import ChunkRecord, Metadata, TensorMetadata  # noqa: F401
from .save_state_dict import save_state_dict, wait_async_save  # noqa: F401
