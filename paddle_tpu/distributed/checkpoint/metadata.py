"""Checkpoint metadata (reference:
/root/reference/python/paddle/distributed/checkpoint/metadata.py —
LocalTensorMetadata/LocalTensorIndex/Metadata describing, for every saved
tensor, the global shape and which file holds which global-offset chunk).

TPU-native: a shard is identified by its global index (tuple of
(start, stop) per dim) taken from ``jax.Array.addressable_shards[i].index``;
the metadata records, per tensor name: global shape, dtype, and the list of
(chunk_index → file, key) mappings. JSON-serialised alongside the data files.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Tuple


@dataclasses.dataclass
class ChunkRecord:
    offsets: List[int]          # global start per dim
    lengths: List[int]          # chunk extent per dim
    file: str                   # data file holding this chunk
    key: str                    # key inside the file


@dataclasses.dataclass
class TensorMetadata:
    global_shape: List[int]
    dtype: str
    chunks: List[ChunkRecord]


@dataclasses.dataclass
class Metadata:
    tensors: Dict[str, TensorMetadata]
    flat_mapping: Optional[Dict[str, str]] = None  # user key -> storage key
    # integrity records per data file: {fname: {"size", "crc32", "sha256"}} —
    # written by save_state_dict, verified by load_state_dict (PT-CKPT codes,
    # docs/RESILIENCE.md). Optional so pre-integrity checkpoints still load.
    files: Optional[Dict[str, Dict]] = None

    def to_json(self) -> str:
        return json.dumps(
            {
                "tensors": {
                    name: {
                        "global_shape": tm.global_shape,
                        "dtype": tm.dtype,
                        "chunks": [dataclasses.asdict(c) for c in tm.chunks],
                    }
                    for name, tm in self.tensors.items()
                },
                "flat_mapping": self.flat_mapping,
                "files": self.files,
            },
            indent=1,
        )

    @classmethod
    def from_json(cls, text: str) -> "Metadata":
        obj = json.loads(text)
        tensors = {
            name: TensorMetadata(
                global_shape=t["global_shape"],
                dtype=t["dtype"],
                chunks=[ChunkRecord(**c) for c in t["chunks"]],
            )
            for name, t in obj["tensors"].items()
        }
        return cls(tensors=tensors, flat_mapping=obj.get("flat_mapping"),
                   files=obj.get("files"))


def index_to_offsets(index: Tuple, shape: Tuple[int, ...]) -> Tuple[List[int], List[int]]:
    """Convert an addressable-shard index (tuple of slices) to offsets/lengths."""
    offsets, lengths = [], []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        offsets.append(start)
        lengths.append(stop - start)
    if not index:  # scalar
        return [], []
    return offsets, lengths
