"""paddle_tpu.distributed.auto_tuner — search over parallelism configs.

Parity anchors: the reference's auto-tuner
(/root/reference/python/paddle/distributed/auto_tuner/tuner.py:21 AutoTuner,
search.py GridSearch, prune.py divisibility/memory prune rules, recorder.py)
which greedily trials dp/mp/pp/sharding/micro-batch combinations.

TPU-native redesign: candidates are factorizations of the chip count into
mesh axes {dp, fsdp, tp, pp, sep} plus microbatch counts. Pruning uses the
model's shape constraints (heads % tp, layers % pp, seq % sep, batch
divisibility) and an analytic HBM-fit model; ranking uses a roofline-style
cost model of per-step compute vs ICI collective volume (the quantities the
scaling-book recipe says matter). An optional live-trial phase measures real
step time through the Engine for the top-K analytic candidates.
"""

from __future__ import annotations

import itertools
import json
import math
import os
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["TuneConfig", "Candidate", "AutoTuner", "Recorder"]


class Recorder:
    """Persistent trial history (reference: auto_tuner/recorder.py History
    — the tuner's record store, resumable across runs).

    Each record: {"key", "axes", "n_micro", "cost", "memory_gb", "metric"
    (step seconds; None for failures), "status" ("ok"|"error")}. Stored as
    JSONL when ``path`` is given, else in-memory. Keys embed a FINGERPRINT
    of the tuned config so one history file shared across different models
    never cross-reuses metrics. Malformed trailing lines (a trial process
    killed mid-append) are skipped, not fatal — resumability must survive
    exactly the crashes it exists for.
    """

    def __init__(self, path: Optional[str] = None, fingerprint: str = ""):
        self.path = path
        self.fingerprint = fingerprint
        self.records: List[dict] = []
        if path and os.path.exists(path):
            with open(path) as f:
                for ln in f:
                    if not ln.strip():
                        continue
                    try:
                        self.records.append(json.loads(ln))
                    except json.JSONDecodeError:
                        continue  # truncated tail from a killed trial

    def key_of(self, c: "Candidate") -> str:
        axes = "x".join(f"{k}{v}" for k, v in sorted(c.axes.items()))
        return f"{self.fingerprint}|{axes}@m{c.n_micro}"

    def seen(self, c: "Candidate") -> bool:
        k = self.key_of(c)
        return any(r.get("key") == k for r in self.records)

    def metric_for(self, c: "Candidate") -> Optional[float]:
        k = self.key_of(c)
        for r in self.records:
            if r.get("key") == k and r.get("status") == "ok":
                return float(r["metric"])
        return None

    def store(self, c: "Candidate", metric: Optional[float],
              status: str = "ok", **extra) -> dict:
        rec = {"key": self.key_of(c), "axes": dict(c.axes),
               "n_micro": c.n_micro, "cost": c.cost,
               "memory_gb": c.memory_gb, "metric": metric,
               "status": status, **extra}
        self.records.append(rec)
        if self.path:
            with open(self.path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        return rec

    def sorted(self) -> List[dict]:
        ok = [r for r in self.records if r.get("status") == "ok"]
        return sorted(ok, key=lambda r: r["metric"])

    def get_best(self) -> Optional[dict]:
        s = self.sorted()
        return s[0] if s else None


@dataclass
class TuneConfig:
    n_devices: int
    # model shape
    num_layers: int
    hidden_size: int
    num_heads: int
    seq_len: int
    global_batch: int
    vocab_size: int = 32000
    ffn_mult: float = 8 / 3  # swiglu default
    # hardware
    hbm_gb: float = 95.0            # v5p per-chip HBM
    ici_gbps: float = 1200.0        # bidirectional per-chip ICI bandwidth
    flops_per_chip: float = 459e12  # bf16 peak
    # training setup
    param_bytes: int = 2            # bf16 params
    opt_state_bytes: int = 8        # fp32 m+v
    grad_bytes: int = 4
    remat: bool = True
    # search space
    max_pp: int = 8
    max_tp: int = 8
    allow_sep: bool = True


@dataclass
class Candidate:
    axes: Dict[str, int]
    n_micro: int
    cost: float = 0.0
    memory_gb: float = 0.0
    details: Dict[str, float] = field(default_factory=dict)

    def __repr__(self):
        a = "x".join(f"{k}{v}" for k, v in self.axes.items() if v > 1) or "single"
        return (f"Candidate({a}, n_micro={self.n_micro}, "
                f"cost={self.cost:.3g}, mem={self.memory_gb:.1f}GB)")


def _factorizations(n: int, axes: Sequence[str]) -> List[Dict[str, int]]:
    """All ways to write n as an ordered product over the axes (every divisor,
    so 12 = dp3×tp4 etc. — non-power-of-two topologies are real)."""
    if not axes:
        return [{}] if n == 1 else []
    out = []
    head, rest = axes[0], axes[1:]
    for d in range(1, n + 1):
        if n % d == 0:
            for tail in _factorizations(n // d, rest):
                out.append({head: d, **tail})
    return out


class AutoTuner:
    """Grid-generate -> prune -> rank -> (optionally) trial.

    >>> tuner = AutoTuner(TuneConfig(n_devices=8, num_layers=16, hidden_size=1024,
    ...                              num_heads=16, seq_len=2048, global_batch=32))
    >>> best = tuner.search()           # analytic
    >>> best = tuner.search(run_fn=f)   # f(Candidate) -> step_time_s, live trials
    """

    AXES = ("dp", "fsdp", "sep", "tp", "pp")

    def __init__(self, config: TuneConfig):
        self.cfg = config
        self.history: List[Tuple[Candidate, float]] = []
        self.recorder: Optional[Recorder] = None
        self._hist_keys: set = set()   # dedup across repeated search() calls

    def _fingerprint(self) -> str:
        """Stable digest over EVERY TuneConfig field — any field can change
        trial outcomes (remat, vocab, hardware caps, ...), so any change
        must invalidate history reuse."""
        import dataclasses
        import hashlib

        blob = json.dumps(dataclasses.asdict(self.cfg), sort_keys=True)
        return hashlib.sha1(blob.encode()).hexdigest()[:12]

    # -- candidate generation (reference: search.py GridSearch) --
    def candidates(self) -> List[Candidate]:
        cfg = self.cfg
        out = []
        for axes in _factorizations(cfg.n_devices, self.AXES):
            for n_micro in (1, 2, 4, 8, 16):
                c = Candidate(axes, n_micro)
                if self._prune(c) is None:
                    c.cost = self._cost(c)
                    out.append(c)
        out.sort(key=lambda c: c.cost)
        return out

    # -- prune rules (reference: prune.py) --
    def _prune(self, c: Candidate) -> Optional[str]:
        cfg, a = self.cfg, c.axes
        dp_total = a["dp"] * a["fsdp"]
        if a["tp"] > cfg.max_tp or a["pp"] > cfg.max_pp:
            return "axis cap"
        if cfg.num_heads % a["tp"]:
            return "heads % tp"
        if cfg.num_layers % a["pp"]:
            return "layers % pp"
        if not cfg.allow_sep and a["sep"] > 1:
            return "sep disabled"
        if cfg.seq_len % a["sep"]:
            return "seq % sep"
        if cfg.hidden_size % a["tp"]:
            return "hidden % tp"
        if cfg.global_batch % (dp_total * c.n_micro):
            return "batch divisibility"
        if a["pp"] == 1 and c.n_micro > 1:
            return "microbatching without pp wastes nothing but trials"
        if a["pp"] > 1 and c.n_micro < a["pp"]:
            return "n_micro < pp starves the pipeline"
        mem = self._memory_gb(c)
        if mem > cfg.hbm_gb * 0.9:
            return "exceeds HBM"
        c.memory_gb = mem
        return None

    # -- analytic models --
    def _param_count(self) -> float:
        cfg = self.cfg
        h, L = cfg.hidden_size, cfg.num_layers
        ffn = int(cfg.ffn_mult * h)
        per_layer = 4 * h * h + 3 * h * ffn + 2 * h  # attn + swiglu + norms
        return L * per_layer + 2 * cfg.vocab_size * h

    def _memory_gb(self, c: Candidate) -> float:
        cfg, a = self.cfg, c.axes
        n_params = self._param_count()
        shard = a["fsdp"] * a["tp"] * a["pp"]
        state = n_params * (cfg.param_bytes + cfg.opt_state_bytes
                            + cfg.grad_bytes) / shard
        # activations: per microbatch per device; remat keeps ~1 layer live
        mb = cfg.global_batch // (a["dp"] * a["fsdp"] * max(c.n_micro, 1))
        seq = cfg.seq_len // a["sep"]
        layers_live = (1 if cfg.remat else cfg.num_layers / a["pp"])
        act = mb * seq * cfg.hidden_size * 2 * 16 * layers_live / a["tp"]
        return (state + act) / 1e9

    def _cost(self, c: Candidate) -> float:
        """Roofline step-time estimate (seconds): max-ish of compute and the
        serial collective volumes over ICI."""
        cfg, a = self.cfg, c.axes
        n_params = self._param_count()
        tokens = cfg.global_batch * cfg.seq_len
        flops = 6.0 * n_params * tokens
        t_compute = flops / (cfg.flops_per_chip * cfg.n_devices)

        bw = cfg.ici_gbps * 1e9 / 8  # bytes/s, rough
        # fsdp: allgather params + reduce-scatter grads each step
        v_fsdp = (2 * n_params * cfg.param_bytes * (a["fsdp"] - 1)
                  / max(a["fsdp"], 1)) / (a["tp"] * a["pp"])
        # tp: 2 allreduces of activations per layer (fwd+bwd ~2x)
        mb_tokens = tokens / (a["dp"] * a["fsdp"] * a["sep"])
        v_tp = (4 * cfg.num_layers * mb_tokens * cfg.hidden_size
                * cfg.param_bytes * (a["tp"] - 1) / max(a["tp"], 1)) if a["tp"] > 1 else 0.0
        # sep: all_to_all around attention per layer
        v_sep = (2 * cfg.num_layers * mb_tokens * cfg.hidden_size
                 * cfg.param_bytes) if a["sep"] > 1 else 0.0
        # pp: bubble fraction extends compute
        bubble = (a["pp"] - 1) / max(c.n_micro + a["pp"] - 1, 1)
        t_comm = (v_fsdp + v_tp + v_sep) / bw
        cost = t_compute * (1 + bubble) + 0.5 * t_comm  # half overlapped
        c.details = {"t_compute": t_compute, "t_comm": t_comm,
                     "bubble": bubble}
        return cost

    # -- search driver (reference: tuner.py AutoTuner.search_once loop) --
    def _note_history(self, c: Candidate, t: float,
                      recorder: Recorder) -> None:
        """Append to ``self.history`` at most once per candidate key —
        repeated ``search()`` calls re-walk the same cached trials, and
        duplicating them would skew anything averaging over history."""
        k = recorder.key_of(c)
        if k not in self._hist_keys:
            self._hist_keys.add(k)
            self.history.append((c, t))

    def _trial(self, c: Candidate, run_fn, recorder: Recorder):
        """One error-tolerant trial with history reuse + recording."""
        cached = recorder.metric_for(c)
        if cached is not None:
            self._note_history(c, cached, recorder)  # resumed: no dup
            return cached
        if recorder.seen(c):
            return None  # previously failed — don't retry (reference prune)
        try:
            t = float(run_fn(c))
        except Exception as e:
            recorder.store(c, None, status="error", error=repr(e)[:200])
            return None
        recorder.store(c, t)
        self._note_history(c, t, recorder)
        return t

    def _neighbors(self, best: Candidate,
                   cands: List[Candidate]) -> List[Candidate]:
        """Local refinement set around the measured best: candidates one
        MOVE away — a factor shifted between two axes (the device product is
        fixed, so the minimal mesh change touches exactly two axes), or the
        same mesh at a different n_micro. The greedy neighborhood step the
        reference's tuner walks after its grid pass."""
        out = []
        for c in cands:
            diff_axes = [k for k in c.axes if c.axes[k] != best.axes[k]]
            # exactly two axes change in a factor move (the device product
            # is fixed, so a single-axis change is impossible)
            if len(diff_axes) == 2 or (not diff_axes
                                       and c.n_micro != best.n_micro):
                out.append(c)
        return out

    def search(self, run_fn: Optional[Callable[[Candidate], float]] = None,
               max_trials: int = 4, history_path: Optional[str] = None,
               refine: bool = True) -> Candidate:
        """Analytic ranking; with ``run_fn``, live trials of the top-K
        followed by a one-axis neighborhood refinement around the measured
        best. Trials are RECORDED (``history_path`` -> JSONL, resumable:
        already-measured candidates reuse their stored metric, failed ones
        are not retried — reference recorder.py semantics)."""
        cands = self.candidates()
        if not cands:
            raise ValueError("no feasible parallel config for this model/mesh")
        if run_fn is None:
            return cands[0]
        if history_path is not None or self.recorder is None:
            recorder = self.recorder = Recorder(
                history_path, fingerprint=self._fingerprint())
        elif self.recorder.path is not None:
            # history_path=None after a FILE-backed search: keep the trial
            # knowledge (failed candidates still not retried) but stop
            # persisting — the caller asked for no file this time
            mem = Recorder(None, fingerprint=self._fingerprint())
            mem.records = list(self.recorder.records)
            recorder = self.recorder = mem
        else:
            # history_path=None on a repeat search: REUSE the in-memory
            # recorder — "failed candidates are not retried" must hold
            # across calls, not just within one
            recorder = self.recorder
        best, best_t = None, math.inf
        for c in cands[:max_trials]:
            t = self._trial(c, run_fn, recorder)
            if t is not None and t < best_t:
                best, best_t = c, t
        if best is not None and refine:
            ranked = {id(c): i for i, c in enumerate(cands)}
            neigh = [c for c in self._neighbors(best, cands)
                     if ranked.get(id(c), 0) >= max_trials]
            neigh.sort(key=lambda c: c.cost)
            for c in neigh[:max_trials]:
                t = self._trial(c, run_fn, recorder)
                if t is not None and t < best_t:
                    best, best_t = c, t
        if best is None:
            raise RuntimeError("every live trial failed")
        return best
