"""Shared retry/timeout/backoff — one policy object for every control-plane
transport (TCPStore client ops, rpc connections, ps push/pull fan-out).

Replaces raise-on-first-EOF: a transient transport failure (peer restarting,
store daemon momentarily unreachable, injected fault) is retried with
exponential backoff + jitter under an overall deadline; exhaustion raises a
:class:`RetryError` carrying a stable ``PT-RETRY-xxx`` diagnostic code so
logs and tests can assert on the failure class, not a message string.

Diagnostic codes (catalogued in docs/RESILIENCE.md):

- ``PT-RETRY-001`` — overall deadline exhausted while retrying
- ``PT-RETRY-002`` — attempt budget exhausted
- Non-retryable exceptions propagate unchanged (a typed ``KeyError`` from a
  store miss must stay a ``KeyError``).

``PT_RETRY_DISABLE=1`` collapses every policy to a single attempt — the
switch ``tools/fault_drill.py`` uses to prove each injected transport fault
flips the exit code when retry is off.
"""

from __future__ import annotations

import dataclasses
import os
import random
import time
from typing import Callable, Optional, Tuple, Type

__all__ = ["RetryPolicy", "RetryError", "retry_call", "DEFAULT_POLICY",
           "retries_disabled"]


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with full jitter under an overall deadline.

    Delay before attempt ``k`` (1-based, first retry is k=2):
    ``min(max_delay, base_delay * multiplier**(k-2))`` scaled by a uniform
    jitter in ``[1-jitter, 1+jitter]``, truncated so the sleep never crosses
    ``deadline``.
    """

    max_attempts: int = 4
    base_delay: float = 0.05
    multiplier: float = 2.0
    max_delay: float = 2.0
    jitter: float = 0.25
    deadline: Optional[float] = None       # seconds across ALL attempts
    retry_on: Tuple[Type[BaseException], ...] = (
        ConnectionError, TimeoutError, OSError)


DEFAULT_POLICY = RetryPolicy()


class RetryError(RuntimeError):
    """Terminal retry failure with a stable diagnostic code.

    Attributes: ``code`` (PT-RETRY-xxx), ``what`` (operation label),
    ``attempts``, ``elapsed``, ``last`` (the final underlying exception).
    """

    def __init__(self, code: str, what: str, attempts: int, elapsed: float,
                 last: BaseException):
        self.code = code
        self.what = what
        self.attempts = attempts
        self.elapsed = elapsed
        self.last = last
        super().__init__(
            f"{code}: {what} failed after {attempts} attempt(s) in "
            f"{elapsed:.2f}s: {last!r}")


def retries_disabled() -> bool:
    return os.environ.get("PT_RETRY_DISABLE") == "1"


def backoff_delays(policy: RetryPolicy, rng: Optional[random.Random] = None):
    """The delay sequence a policy produces (attempt 2, 3, ...) — exposed so
    tests can pin the schedule without sleeping."""
    r = rng or random
    d = policy.base_delay
    for _ in range(max(0, policy.max_attempts - 1)):
        j = 1.0 + policy.jitter * (2.0 * r.random() - 1.0) if policy.jitter else 1.0
        yield min(policy.max_delay, d) * j
        d *= policy.multiplier


def retry_call(fn: Callable, *args, policy: Optional[RetryPolicy] = None,
               what: str = "call", on_retry: Optional[Callable] = None,
               rng: Optional[random.Random] = None, sleep=time.sleep, **kwargs):
    """Call ``fn(*args, **kwargs)``, retrying ``policy.retry_on`` failures.

    ``on_retry(attempt, exc, delay)`` is invoked before each backoff sleep
    (reconnect hooks, logging). ``sleep`` is injectable for tests.
    """
    pol = policy or DEFAULT_POLICY
    attempts = 1 if retries_disabled() else max(1, pol.max_attempts)
    start = time.monotonic()
    delays = backoff_delays(pol, rng)
    last: Optional[BaseException] = None
    for attempt in range(1, attempts + 1):
        try:
            return fn(*args, **kwargs)
        except pol.retry_on as e:
            last = e
            elapsed = time.monotonic() - start
            if attempt >= attempts:
                if attempts == 1:
                    raise        # retries disabled/single-shot: raw failure
                raise RetryError("PT-RETRY-002", what, attempt, elapsed, e) from e
            delay = next(delays, pol.max_delay)
            if pol.deadline is not None:
                remain = pol.deadline - elapsed
                if remain <= 0:
                    raise RetryError("PT-RETRY-001", what, attempt, elapsed,
                                     e) from e
                delay = min(delay, remain)
            if on_retry is not None:
                on_retry(attempt, e, delay)
            sleep(max(0.0, delay))
    raise AssertionError("unreachable")  # loop always returns or raises
