"""Shared retry/timeout/backoff — one policy object for every control-plane
transport (TCPStore client ops, rpc connections, ps push/pull fan-out).

Replaces raise-on-first-EOF: a transient transport failure (peer restarting,
store daemon momentarily unreachable, injected fault) is retried with
exponential backoff + jitter under an overall deadline; exhaustion raises a
:class:`RetryError` carrying a stable ``PT-RETRY-xxx`` diagnostic code so
logs and tests can assert on the failure class, not a message string.

Diagnostic codes (catalogued in docs/RESILIENCE.md):

- ``PT-RETRY-001`` — overall deadline exhausted while retrying
- ``PT-RETRY-002`` — attempt budget exhausted
- Non-retryable exceptions propagate unchanged (a typed ``KeyError`` from a
  store miss must stay a ``KeyError``).

``PT_RETRY_DISABLE=1`` collapses every policy to a single attempt — the
switch ``tools/fault_drill.py`` uses to prove each injected transport fault
flips the exit code when retry is off.

Every ``retry_call`` also feeds a module-level stats registry
(:func:`retry_stats`): calls / attempts / retries / give-ups and cumulative
latency, plus a bounded per-``what`` attempt breakdown — the raw material
for the observability layer. The serving engine surfaces a snapshot in
``ContinuousBatchingEngine.stats`` and ``tools/fault_drill.py`` prints it
after the selftest matrix.
"""

from __future__ import annotations

import dataclasses
import os
import random
import threading
import time
from typing import Callable, Optional, Tuple, Type

__all__ = ["RetryPolicy", "RetryError", "retry_call", "DEFAULT_POLICY",
           "retries_disabled", "retry_stats", "reset_retry_stats"]

# -- stats registry ---------------------------------------------------------
# retry_call runs CONCURRENTLY: fleet ``parallel_step`` replica threads,
# the rpc ThreadPoolExecutor fan-out and the elastic heartbeat daemon all
# funnel through it, so the read-modify-write counter updates need a real
# lock — ``+=`` under the GIL loses increments across threads
# (PT-RACE-001, tools/lint_concurrency.py; regression:
# tests/test_resilience.py::test_retry_stats_concurrent_exact). Still a
# control-plane path — the lock is ~100ns per attempt, invisible next to
# a socket round trip. ``by_what`` is bounded so an unbounded label space
# (per-key store ops) cannot grow the registry without limit.
_BY_WHAT_CAP = 64

_STATS_LOCK = threading.Lock()
_STATS = {"calls": 0, "attempts": 0, "retries": 0, "giveups": 0,
          "latency_s": 0.0}
_BY_WHAT: dict = {}


def retry_stats() -> dict:
    """Snapshot of the registry: aggregate counters plus the per-``what``
    attempt counts (``by_what``, capped at 64 distinct labels)."""
    with _STATS_LOCK:
        out = dict(_STATS)
        out["by_what"] = dict(_BY_WHAT)
    return out


def reset_retry_stats() -> None:
    with _STATS_LOCK:
        for k in _STATS:
            _STATS[k] = 0.0 if k == "latency_s" else 0
        _BY_WHAT.clear()


def _note_attempt(what: str) -> None:
    with _STATS_LOCK:
        _STATS["attempts"] += 1
        if what in _BY_WHAT or len(_BY_WHAT) < _BY_WHAT_CAP:
            _BY_WHAT[what] = _BY_WHAT.get(what, 0) + 1


def _note(key: str, amount=1) -> None:
    with _STATS_LOCK:
        _STATS[key] += amount


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with full jitter under an overall deadline.

    Delay before attempt ``k`` (1-based, first retry is k=2):
    ``min(max_delay, base_delay * multiplier**(k-2))`` scaled by a uniform
    jitter in ``[1-jitter, 1+jitter]``, truncated so the sleep never crosses
    ``deadline``.
    """

    max_attempts: int = 4
    base_delay: float = 0.05
    multiplier: float = 2.0
    max_delay: float = 2.0
    jitter: float = 0.25
    deadline: Optional[float] = None       # seconds across ALL attempts
    retry_on: Tuple[Type[BaseException], ...] = (
        ConnectionError, TimeoutError, OSError)


DEFAULT_POLICY = RetryPolicy()


class RetryError(RuntimeError):
    """Terminal retry failure with a stable diagnostic code.

    Attributes: ``code`` (PT-RETRY-xxx), ``what`` (operation label),
    ``attempts``, ``elapsed``, ``last`` (the final underlying exception).
    """

    def __init__(self, code: str, what: str, attempts: int, elapsed: float,
                 last: BaseException):
        self.code = code
        self.what = what
        self.attempts = attempts
        self.elapsed = elapsed
        self.last = last
        super().__init__(
            f"{code}: {what} failed after {attempts} attempt(s) in "
            f"{elapsed:.2f}s: {last!r}")


def retries_disabled() -> bool:
    return os.environ.get("PT_RETRY_DISABLE") == "1"


def backoff_delays(policy: RetryPolicy, rng: Optional[random.Random] = None):
    """The delay sequence a policy produces (attempt 2, 3, ...) — exposed so
    tests can pin the schedule without sleeping."""
    r = rng or random
    d = policy.base_delay
    for _ in range(max(0, policy.max_attempts - 1)):
        j = 1.0 + policy.jitter * (2.0 * r.random() - 1.0) if policy.jitter else 1.0
        yield min(policy.max_delay, d) * j
        d *= policy.multiplier


def retry_call(fn: Callable, *args, policy: Optional[RetryPolicy] = None,
               what: str = "call", on_retry: Optional[Callable] = None,
               rng: Optional[random.Random] = None, sleep=time.sleep, **kwargs):
    """Call ``fn(*args, **kwargs)``, retrying ``policy.retry_on`` failures.

    ``on_retry(attempt, exc, delay)`` is invoked before each backoff sleep
    (reconnect hooks, logging). ``sleep`` is injectable for tests.
    """
    pol = policy or DEFAULT_POLICY
    attempts = 1 if retries_disabled() else max(1, pol.max_attempts)
    start = time.monotonic()
    delays = backoff_delays(pol, rng)
    last: Optional[BaseException] = None
    _note("calls")
    for attempt in range(1, attempts + 1):
        _note_attempt(what)
        try:
            result = fn(*args, **kwargs)
            _note("latency_s", time.monotonic() - start)
            return result
        except pol.retry_on as e:
            last = e
            elapsed = time.monotonic() - start
            if attempt >= attempts:
                _note("giveups")
                _note("latency_s", elapsed)
                if attempts == 1:
                    raise        # retries disabled/single-shot: raw failure
                raise RetryError("PT-RETRY-002", what, attempt, elapsed, e) from e
            delay = next(delays, pol.max_delay)
            if pol.deadline is not None:
                remain = pol.deadline - elapsed
                if remain <= 0:
                    _note("giveups")
                    _note("latency_s", elapsed)
                    raise RetryError("PT-RETRY-001", what, attempt, elapsed,
                                     e) from e
                delay = min(delay, remain)
            _note("retries")
            if on_retry is not None:
                on_retry(attempt, e, delay)
            sleep(max(0.0, delay))
    raise AssertionError("unreachable")  # loop always returns or raises
