"""Checkpoint lifecycle — the train → serve handoff (docs/RESILIENCE.md
"Checkpoint lifecycle").

The checkpoint is the one artifact that crosses every subsystem boundary:
the optimizer writes it (``ResilientTrainer`` + async ``save_state_dict``),
an elastic resume reshards it onto the surviving mesh, and — with this
module — a :class:`CheckpointPublisher` hands it to the serving fleet.
Publishing is three fenced moves:

1. **verify** — every shard file named by the ``0.metadata`` manifest is
   digest-checked (crc32 + sha256 + size, replica fallback included) before
   a single byte reaches a model. A checkpoint that cannot prove itself is
   refused with the same typed ``PT-CKPT`` errors the loader raises.
2. **load** — trained params map into the SERVING model's weight pytree in
   place (``load_state_dict`` on ``{"model": model.state_dict()}``: the
   optimizer's m/v/step stay behind, the live ``Tensor`` objects every
   engine was built around are filled under their current shardings).
3. **swap** — ``fleet.rolling_restart()`` drains and rebuilds one replica
   at a time; each rebuilt engine snapshots the (now updated) weights at
   construction, so traffic never sees a half-updated replica and the
   swapped fleet is bit-equal to a cold fleet built from the published
   checkpoint.

Publishes are **generation-fenced on both sides**: the trainer's LATEST
commit carries a monotonic token (``checkpoint/latest.py``, PT-CKPT-005),
and the publisher refuses to publish a generation below the one it already
served — a zombie pre-shrink writer can neither rewind the resume pointer
nor roll the fleet back to its stale weights.

Module-level lifecycle stats feed ``pt_checkpoint_generation`` /
``pt_checkpoint_publish_total`` / ``pt_checkpoint_publish_failures`` /
``pt_lifecycle_phase`` via ``observability.checkpoint_collector`` (REQUIRED
in ``tools/scrape_metrics.py --selftest``); the full drill arc lives in
``tools/fault_drill.py --drill lifecycle_e2e``.

This module imports nothing heavy at import time (the collector touches it
from scrape threads); jax-facing work is deferred into the methods.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional, Tuple

__all__ = ["CheckpointPublisher", "LIFECYCLE_PHASES", "lifecycle_stats",
           "reset_lifecycle_stats", "set_lifecycle_phase"]

#: the drill's state machine (docs/RESILIENCE.md lifecycle section)
LIFECYCLE_PHASES = ("idle", "train", "checkpoint", "shrink", "resume",
                    "publish", "serve")

# shared by trainer threads, the publisher and scrape threads (PT-RACE)
_STATS_LOCK = threading.Lock()
_STATS = {
    "generation": 0,          # newest generation successfully published
    "publish_total": 0,
    "publish_failures": 0,
    "phase": "idle",
}


def lifecycle_stats() -> Dict[str, object]:
    """Snapshot for the checkpoint collector (zero-state renders at 0)."""
    with _STATS_LOCK:
        return dict(_STATS)


def reset_lifecycle_stats() -> None:
    with _STATS_LOCK:
        _STATS.update(generation=0, publish_total=0, publish_failures=0,
                      phase="idle")


def set_lifecycle_phase(phase: str) -> None:
    """Advance the drill's phase marker (``pt_lifecycle_phase``)."""
    if phase not in LIFECYCLE_PHASES:
        raise ValueError(f"unknown lifecycle phase {phase!r} "
                         f"(one of {LIFECYCLE_PHASES})")
    with _STATS_LOCK:
        _STATS["phase"] = phase


class CheckpointPublisher:
    """Hand a training checkpoint to a serving fleet, fenced and verified.

    Args:
        ckpt_dir: the ``ResilientTrainer`` checkpoint root (``step_<n>/``
            dirs plus the generation-bearing ``LATEST`` pointer).
        tracer: optional :class:`~paddle_tpu.observability.TraceRecorder`;
            every publish lands as a ``publish`` span (step, generation,
            shard count, outcome).

    The publisher is itself a fence: :meth:`publish` refuses (typed
    :class:`StaleGenerationError`) any checkpoint whose generation is below
    the newest one this publisher already served, so a late call from a
    zombie trainer cannot roll live weights backwards.
    """

    def __init__(self, ckpt_dir: str, *, tracer=None):
        self.ckpt_dir = str(ckpt_dir)
        self.tracer = tracer
        self._lock = threading.Lock()
        self._published: Optional[Tuple[int, int]] = None   # (step, gen)

    # -- pointer ----------------------------------------------------------
    def latest(self) -> Optional[Tuple[int, int]]:
        """The committed ``(step, generation)`` resume pointer, or None."""
        from ..checkpoint.latest import read_latest

        return read_latest(self.ckpt_dir)

    def step_dir(self, step: int) -> str:
        return os.path.join(self.ckpt_dir, f"step_{step:08d}")

    # -- the three moves --------------------------------------------------
    def verify(self, step: int) -> int:
        """Digest-check every shard the manifest names (replica fallback
        honored). Returns the number of verified shard files; raises the
        loader's typed ``PT-CKPT`` errors on damage, ``FileNotFoundError``
        on a missing manifest (torn/absent checkpoint)."""
        import json

        from ..checkpoint.load_state_dict import _ChunkReader

        path = self.step_dir(step)
        with open(os.path.join(path, "0.metadata")) as f:
            files = json.load(f).get("files") or {}
        reader = _ChunkReader(path, files=files, verify=True)
        for fname in sorted(files):
            reader._verified_path(fname)
        return len(files)

    def load_weights(self, model, step: int) -> int:
        """Map the checkpoint's trained params into ``model`` IN PLACE
        (the serving engines' weight pytree — m/v/step are not touched).
        Returns the number of parameter tensors filled."""
        from ..checkpoint import load_state_dict

        target = {"model": model.state_dict()}
        load_state_dict(target, self.step_dir(step))
        return len(target["model"])

    def publish(self, model, fleet=None, *, step: Optional[int] = None,
                verify: bool = True) -> Dict[str, object]:
        """Verify → load → hot-swap. ``step=None`` publishes the committed
        LATEST pointer. ``fleet`` (a ``FleetRouter`` or anything with
        ``rolling_restart()``) is swapped replica-by-replica under
        traffic; ``fleet=None`` just updates the model (callers owning
        their own engines rebuild them).

        Returns ``{"step", "generation", "shards", "params", "time_s"}``.
        On any failure the fleet keeps its previous weights* and
        ``publish_failures`` is incremented. (*verification happens before
        the in-place load touches the model, so a corrupt checkpoint is
        refused with the serving weights intact.)
        """
        from ..checkpoint.latest import StaleGenerationError

        t0 = time.monotonic()
        if step is None:
            rec = self.latest()
            if rec is None:
                raise FileNotFoundError(
                    f"no committed LATEST pointer in {self.ckpt_dir}")
            step, gen = rec
        else:
            rec = self.latest()
            gen = rec[1] if rec is not None and rec[0] == step else 0
        set_lifecycle_phase("publish")
        try:
            with self._lock:
                if (self._published is not None
                        and gen < self._published[1]):
                    raise StaleGenerationError(
                        self.ckpt_dir, self._published[1], gen)
            shards = self.verify(step) if verify else 0
            params = self.load_weights(model, step)
            if fleet is not None:
                fleet.rolling_restart()
            with self._lock:
                self._published = (step, gen)
        except BaseException:
            with _STATS_LOCK:
                _STATS["publish_failures"] += 1
            if self.tracer is not None:
                self.tracer.publish(t0, step, gen, 0, ok=False)
            raise
        with _STATS_LOCK:
            _STATS["publish_total"] += 1
            _STATS["generation"] = max(_STATS["generation"], gen)
        set_lifecycle_phase("serve")
        dt = time.monotonic() - t0
        if self.tracer is not None:
            self.tracer.publish(t0, step, gen, shards, ok=True)
        return {"step": step, "generation": gen, "shards": shards,
                "params": params, "time_s": dt}
