"""Watchdogs: numeric-anomaly policy and wall-clock step budgets.

Two independent control planes live here:

- :class:`NumericWatchdog` — host-side policy over the on-device health
  word (loss spikes / NaNs; docs/NUMERIC_GUARD.md).
- :class:`StepWatchdog` — a threaded wall-clock budget for serving engine
  steps (docs/SERVING.md): a step that overruns its budget is flagged
  **while it is still stuck** (PT-SRV-002), so the supervisor can alert and
  rebuild-from-journal the moment the step finally returns — or an external
  monitor can observe ``fired`` mid-hang.

Loss-spike / NaN watchdog — host-side policy over the device health word.

The jitted train step computes one int32 health word per step
(``framework.numeric_guard.guard_step``); this watchdog is the control
plane that decides what the word *means* for the run, per the engine's
:class:`~paddle_tpu.framework.numeric_guard.GuardPolicy`:

- ``warn``       — log and continue (the update was applied);
- ``skip_step``  — the in-graph zero-apply already protected params and
  optimizer moments; count the skip against ``max_skips_per_window`` and
  escalate to rollback when the window's budget is blown (an isolated bad
  batch is skippable; a *streak* means the trajectory is sick);
- ``rollback``   — restore the last committed checkpoint (the PR-2 ring in
  ``ResilientTrainer``), deterministically re-seed, re-warm the LR over
  ``rewarm_steps``; bounded by ``max_rollbacks`` then abort;
- ``abort``      — raise :class:`NumericAnomalyError`.

Large-model practice (OPT-175B / BLOOM training logs) is exactly this
skip-and-rollback-with-LR-rewarm loop; here it is a policy object with a
seeded fault drill proving each path (``tools/fault_drill.py``).
"""

from __future__ import annotations

import threading
import time
import warnings
from typing import List, Optional, Tuple

from ...framework.numeric_guard import GuardPolicy, describe_health

__all__ = ["NumericWatchdog", "StepWatchdog"]


class StepWatchdog:
    """Wall-clock budget per monitored step (serving engine steps).

    Usage::

        wd = StepWatchdog(budget_s=0.5)
        wd.arm("step:7")
        engine.step()                  # may stall/hang
        if wd.disarm():                # True: the step overran its budget
            supervisor.rebuild()       # PT-SRV-002 path

    A single daemon thread watches the armed window; when the budget
    elapses with the step still running it sets :attr:`fired` and records
    ``(tag, elapsed_at_flag)`` in :attr:`overruns` — the flag is visible
    *during* the hang, not only after the step returns.  ``disarm``
    returns whether the just-finished step overran (by flag or by final
    wall time, so an overrun is never missed even if the thread was slow
    to wake) and re-arms cleanly for the next step.
    """

    def __init__(self, budget_s: float):
        self.budget_s = float(budget_s)
        self.fired = False
        self.overruns: List[Tuple[str, float]] = []
        self._cond = threading.Condition()
        self._armed: Optional[Tuple[str, float]] = None
        self._closed = False
        self._thread = threading.Thread(target=self._watch, daemon=True,
                                        name="serving-step-watchdog")
        self._thread.start()

    def arm(self, tag: str = "") -> None:
        with self._cond:
            self.fired = False
            self._armed = (str(tag), time.monotonic())
            self._cond.notify_all()

    def disarm(self) -> bool:
        with self._cond:
            armed, self._armed = self._armed, None
            self._cond.notify_all()
            if armed is None:
                return False
            tag, t0 = armed
            elapsed = time.monotonic() - t0
            if elapsed > self.budget_s and not self.fired:
                # thread didn't wake in time — account the overrun here
                self.fired = True
                self.overruns.append((tag, elapsed))
            return self.fired

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._armed = None
            self._cond.notify_all()
        self._thread.join(timeout=1.0)

    # -- monitor thread ----------------------------------------------------
    def _watch(self) -> None:
        with self._cond:
            while not self._closed:
                if self._armed is None:
                    self._cond.wait()
                    continue
                tag, t0 = self._armed
                remain = self.budget_s - (time.monotonic() - t0)
                if remain > 0:
                    self._cond.wait(timeout=remain)
                    continue
                if self._armed is not None and self._armed[1] == t0 \
                        and not self.fired:
                    self.fired = True
                    self.overruns.append((tag, time.monotonic() - t0))
                    warnings.warn(
                        f"PT-SRV-002: engine step {tag!r} exceeded its "
                        f"{self.budget_s:.3f}s budget and is still running "
                        "— stall suspected", RuntimeWarning)
                # wait for disarm/re-arm before watching again
                while self._armed is not None and self._armed[1] == t0 \
                        and not self._closed:
                    self._cond.wait()


class NumericWatchdog:
    """Per-run anomaly bookkeeping. ``observe`` returns the decision for one
    step: ``"ok" | "warn" | "skip_step" | "rollback" | "abort"``."""

    def __init__(self, policy: GuardPolicy):
        self.policy = policy
        self.events: List[Tuple[int, int]] = []      # (step, health_word)
        self.skipped_steps: List[int] = []
        self.rollbacks = 0
        self._window_skips: List[int] = []
        self._rewarm_from: Optional[int] = None

    # -- decisions ---------------------------------------------------------
    def observe(self, step: int, word: int) -> str:
        word = int(word)
        if word == 0:
            return "ok"
        self.events.append((int(step), word))
        act = self.policy.action
        if act == GuardPolicy.WARN:
            warnings.warn(
                f"[numeric_guard] step {step}: {describe_health(word)} "
                "(policy=warn, update applied)")
            return "warn"
        if act == GuardPolicy.ABORT:
            return "abort"
        if act == GuardPolicy.ROLLBACK:
            return self._rollback_or_abort()
        # SKIP_STEP: prune the window, then charge this skip against it
        lo = int(step) - self.policy.window
        self._window_skips = [s for s in self._window_skips if s > lo]
        self._window_skips.append(int(step))
        if len(self._window_skips) > self.policy.max_skips_per_window:
            return self._rollback_or_abort()
        self.skipped_steps.append(int(step))
        return "skip_step"

    def _rollback_or_abort(self) -> str:
        return ("abort" if self.rollbacks >= self.policy.max_rollbacks
                else "rollback")

    # -- rollback / LR re-warm bookkeeping ---------------------------------
    def note_rollback(self, resumed_step: int) -> None:
        """Called after the trainer restored a checkpoint at
        ``resumed_step``: charges the rollback budget, clears the skip
        window (the streak's cause was discarded with the state), and arms
        the LR re-warm ramp."""
        self.rollbacks += 1
        self._window_skips = []
        if self.policy.rewarm_steps > 0:
            self._rewarm_from = int(resumed_step)

    def lr_scale(self, step: int) -> float:
        """LR multiplier for ``step``: a linear 1/k .. k/k ramp over the
        ``rewarm_steps`` steps after a rollback, 1.0 otherwise."""
        if self._rewarm_from is None:
            return 1.0
        k = self.policy.rewarm_steps
        i = int(step) - self._rewarm_from
        if i >= k:
            self._rewarm_from = None
            return 1.0
        return float(max(0, i) + 1) / float(k)
