"""Loss-spike / NaN watchdog — host-side policy over the device health word.

The jitted train step computes one int32 health word per step
(``framework.numeric_guard.guard_step``); this watchdog is the control
plane that decides what the word *means* for the run, per the engine's
:class:`~paddle_tpu.framework.numeric_guard.GuardPolicy`:

- ``warn``       — log and continue (the update was applied);
- ``skip_step``  — the in-graph zero-apply already protected params and
  optimizer moments; count the skip against ``max_skips_per_window`` and
  escalate to rollback when the window's budget is blown (an isolated bad
  batch is skippable; a *streak* means the trajectory is sick);
- ``rollback``   — restore the last committed checkpoint (the PR-2 ring in
  ``ResilientTrainer``), deterministically re-seed, re-warm the LR over
  ``rewarm_steps``; bounded by ``max_rollbacks`` then abort;
- ``abort``      — raise :class:`NumericAnomalyError`.

Large-model practice (OPT-175B / BLOOM training logs) is exactly this
skip-and-rollback-with-LR-rewarm loop; here it is a policy object with a
seeded fault drill proving each path (``tools/fault_drill.py``).
"""

from __future__ import annotations

import warnings
from typing import List, Optional, Tuple

from ...framework.numeric_guard import GuardPolicy, describe_health

__all__ = ["NumericWatchdog"]


class NumericWatchdog:
    """Per-run anomaly bookkeeping. ``observe`` returns the decision for one
    step: ``"ok" | "warn" | "skip_step" | "rollback" | "abort"``."""

    def __init__(self, policy: GuardPolicy):
        self.policy = policy
        self.events: List[Tuple[int, int]] = []      # (step, health_word)
        self.skipped_steps: List[int] = []
        self.rollbacks = 0
        self._window_skips: List[int] = []
        self._rewarm_from: Optional[int] = None

    # -- decisions ---------------------------------------------------------
    def observe(self, step: int, word: int) -> str:
        word = int(word)
        if word == 0:
            return "ok"
        self.events.append((int(step), word))
        act = self.policy.action
        if act == GuardPolicy.WARN:
            warnings.warn(
                f"[numeric_guard] step {step}: {describe_health(word)} "
                "(policy=warn, update applied)")
            return "warn"
        if act == GuardPolicy.ABORT:
            return "abort"
        if act == GuardPolicy.ROLLBACK:
            return self._rollback_or_abort()
        # SKIP_STEP: prune the window, then charge this skip against it
        lo = int(step) - self.policy.window
        self._window_skips = [s for s in self._window_skips if s > lo]
        self._window_skips.append(int(step))
        if len(self._window_skips) > self.policy.max_skips_per_window:
            return self._rollback_or_abort()
        self.skipped_steps.append(int(step))
        return "skip_step"

    def _rollback_or_abort(self) -> str:
        return ("abort" if self.rollbacks >= self.policy.max_rollbacks
                else "rollback")

    # -- rollback / LR re-warm bookkeeping ---------------------------------
    def note_rollback(self, resumed_step: int) -> None:
        """Called after the trainer restored a checkpoint at
        ``resumed_step``: charges the rollback budget, clears the skip
        window (the streak's cause was discarded with the state), and arms
        the LR re-warm ramp."""
        self.rollbacks += 1
        self._window_skips = []
        if self.policy.rewarm_steps > 0:
            self._rewarm_from = int(resumed_step)

    def lr_scale(self, step: int) -> float:
        """LR multiplier for ``step``: a linear 1/k .. k/k ramp over the
        ``rewarm_steps`` steps after a rollback, 1.0 otherwise."""
        if self._rewarm_from is None:
            return 1.0
        k = self.policy.rewarm_steps
        i = int(step) - self._rewarm_from
        if i >= k:
            self._rewarm_from = None
            return 1.0
        return float(max(0, i) + 1) / float(k)
