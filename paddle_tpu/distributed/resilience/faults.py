"""Deterministic fault injection — failure as a testable input.

Reference posture: the survey's elastic layer (fleet/elastic/manager.py:125)
defines fault tolerance as "restart from checkpoint between min/max nranks"
but offers no way to *exercise* the recovery paths. This module makes every
failure mode a seeded, step-indexed plan so recovery is proven by tests and
by ``tools/fault_drill.py``, not assumed.

A :class:`FaultPlan` is a list of :class:`FaultSpec` entries. Each spec
watches one injection *site* (a short dotted name, e.g. ``store.client``)
and fires on the ``at``-th matching event for ``count`` events. Sites are
consulted by production code through two hooks:

- :func:`maybe_inject` — control-flow faults: ``kill`` (raises
  :class:`FaultInjected`, a ``ConnectionError``), ``stall``/``delay``
  (sleeps ``arg`` seconds), ``error`` (raises ``RuntimeError``).
- :func:`corrupt` — data faults applied to a byte payload: ``bitflip``
  (flips ``arg`` pseudo-random bits, positions drawn from the plan's seeded
  RNG), ``truncate`` (drops the last ``arg`` bytes), ``garbage`` (replaces
  the payload with seeded random bytes of the same length).

- :func:`numeric_inject_code` / :func:`poison_arrays` — numeric faults
  (PR-3, docs/NUMERIC_GUARD.md): ``nan_grad`` and ``loss_spike`` resolve to
  an in-graph injection code the guarded Engine step consumes as a traced
  scalar (no retrace, detectable only by the on-device health word);
  ``poison_batch`` NaNs seeded positions of the host batch before it ships.

Known sites (see docs/RESILIENCE.md for the catalogue):

====================  =====================================================
``store.client``      before every TCPStore client op (detail ``op:key``)
``store.daemon``      pure-Python store daemon, before serving a command
``elastic.heartbeat`` before each heartbeat write (detail = node_id)
``checkpoint.shard``  shard bytes about to be written (detail = file name)
``collective``        blocking collective entry (detail = op name)
``rpc.connect``       before an rpc client connection (detail = worker)
``numeric.step``      guarded Engine train step (detail = host step index)
``data.batch``        trainer data path, batch about to ship (detail = step)
``serving.block_pool``  serving admission, before block allocation
                        (detail = ``rid:<id>``; ``exhaust`` holds ``arg``
                        free KV blocks — seeded pool exhaustion)
``serving.step``      serving engine, top of every ``step()`` (detail =
                      ``step:<n>``; ``kill`` crashes the engine mid-wave —
                      the ServingSupervisor's rebuild-from-journal drill)
``serving.stall``     same event stream as ``serving.step`` but consulted
                      first (``stall`` hangs the step past its wall-clock
                      budget — the StepWatchdog / PT-SRV-002 drill)
``fleet.replica_kill``  fleet router, before each replica's supervisor
                        step (detail = ``replica:<i>:step:<n>``; ``kill``
                        = replica process death — the journal-backed
                        failover drill, PT-FLT-001)
``fleet.drain``       fleet router, top of every fleet step per replica
                      (same detail; ``kill`` = operator drain signal —
                      the rolling drain/restart drill, PT-FLT-002)
``serving.kv_transfer``  tiered router, migrated KV-chain artifact in
                         transit between tiers (detail = ``rid:<id>``;
                         ``bitflip`` corrupts page bytes — the
                         PT-SRV-007 kv_migration_corruption drill)
``net.connect``       ChaosTransport, before a transport connect
                      (detail = ``peer``; ``drop``/``kill`` refuse it)
``net.send``          ChaosTransport, frame about to ship (detail =
                      ``peer:MSGTYPE``; ``drop`` loses the frame,
                      ``duplicate`` delivers it twice, ``torn`` ships a
                      prefix, ``bitflip`` flips payload bits UNDER the
                      frame crc, ``blackhole`` swallows every later
                      frame to that peer — the net_flaky_migration drill)
``net.recv``          ChaosTransport, before a frame is awaited (detail
                      = ``peer``; same actions on the receive side —
                      ``stall`` holds the receive, the net_slow_peer
                      drill)
``device.loss``       serving engine, same event stream as
                      ``serving.step`` (detail = ``step:<n>``; ``lose``
                      removes ``arg`` devices from the engine's tp mesh
                      — the engine raises ``MeshDegraded``/PT-SRV-008
                      and the elastic supervisor reshards to the widest
                      surviving width, the mesh_device_loss drill)
====================  =====================================================

With no plan installed every hook is a cheap no-op (one global read), so
production paths carry no overhead when fault injection is off.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import List, Optional, Sequence

__all__ = ["FaultSpec", "FaultPlan", "ComposedFaultPlan", "FaultInjected",
           "maybe_inject", "corrupt", "active_plan", "numeric_inject_code",
           "poison_arrays", "resource_hold", "wire_faults", "device_loss"]


class FaultInjected(ConnectionError):
    """Raised by a ``kill`` fault — a ConnectionError so transport-level
    retry paths treat it exactly like a real peer loss / EOF."""


@dataclasses.dataclass
class FaultSpec:
    """One planned fault: ``action`` at the ``at``-th matching event of
    ``site`` (events whose detail contains ``match``), for ``count`` events
    (-1 = every event from ``at`` on)."""

    site: str
    action: str            # kill | stall | delay | error | bitflip | truncate | garbage | drop | duplicate | torn | blackhole
    at: int = 0
    count: int = 1
    arg: float = 0.0       # seconds (stall/delay) or bytes/bits (data faults)
    match: str = ""

    _CONTROL = ("kill", "stall", "delay", "error")
    _DATA = ("bitflip", "truncate", "garbage")
    _NUMERIC = ("nan_grad", "loss_spike", "poison_batch")
    _RESOURCE = ("exhaust",)
    _NET = ("drop", "duplicate", "torn", "blackhole")
    _DEVICE = ("lose",)

    def __post_init__(self):
        known = (self._CONTROL + self._DATA + self._NUMERIC
                 + self._RESOURCE + self._NET + self._DEVICE)
        if self.action not in known:
            raise ValueError(
                f"unknown fault action {self.action!r} (choose: {known})")


class FaultPlan:
    """Seeded, step-indexed fault schedule.

    >>> plan = FaultPlan(seed=7, specs=[
    ...     FaultSpec("store.client", "kill", at=3, count=1)])
    >>> plan.install()         # hooks consult it from now on
    >>> ...
    >>> plan.uninstall()

    Determinism: event counters are per-spec, advancing only on matching
    events, and every random choice (bit positions, garbage bytes) comes
    from ``random.Random(seed)`` — the same plan over the same event stream
    injects byte-identical faults.
    """

    def __init__(self, seed: int = 0, specs: Sequence[FaultSpec] = ()):
        self.seed = int(seed)
        self.specs = list(specs)
        self.rng = random.Random(self.seed)
        self.log: List[tuple] = []          # (site, detail, action) fired
        self._counts = [0] * len(self.specs)
        self._lock = threading.Lock()

    # -- event matching ----------------------------------------------------
    def fire(self, site: str, detail: str = "") -> List[FaultSpec]:
        """Advance counters for this event; return the specs due now."""
        due = []
        with self._lock:
            for i, s in enumerate(self.specs):
                if s.site != site or (s.match and s.match not in detail):
                    continue
                idx = self._counts[i]
                self._counts[i] = idx + 1
                if idx >= s.at and (s.count < 0 or idx < s.at + s.count):
                    due.append(s)
                    self.log.append((site, detail, s.action))
        return due

    def rng_for(self, spec: FaultSpec) -> random.Random:
        """The RNG a data hook draws from when ``spec`` fires. The base
        plan shares one seeded stream across every spec — fine for one
        site at a time, but concurrent sites would interleave draws in
        thread-scheduling order. :class:`ComposedFaultPlan` overrides this
        with per-spec derived streams."""
        return self.rng

    def fired(self) -> dict:
        """``{site: times fired}`` snapshot of the log (drill assertions
        use it to prove every scheduled site actually fired)."""
        with self._lock:
            out = {}
            for site, _detail, _action in self.log:
                out[site] = out.get(site, 0) + 1
            return out

    # -- lifecycle ---------------------------------------------------------
    def install(self) -> "FaultPlan":
        global _ACTIVE
        _ACTIVE = self
        return self

    def uninstall(self) -> None:
        global _ACTIVE
        if _ACTIVE is self:
            _ACTIVE = None

    def __enter__(self) -> "FaultPlan":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


class ComposedFaultPlan(FaultPlan):
    """One seeded plan scheduling MULTIPLE fault sites concurrently — the
    chaos arm of the lifecycle drill (store stall + heartbeat loss +
    shard-write damage + replica kill in one run).

    The base plan is already correct for concurrent *control* faults (the
    per-spec counters advance under the plan lock), but its *data* faults
    share one RNG stream: two sites corrupting bytes from different
    threads would interleave their draws in scheduler order and the
    injected damage would differ run to run. Here every spec gets its own
    stream derived from ``(seed, spec index, site, action)`` — each site's
    events are serialized by the site itself (one writer thread per shard
    file, one heartbeat loop per node), so per-spec draws replay in event
    order and the same composed plan over the same event streams injects
    byte-identical faults regardless of cross-site thread interleaving.

    >>> plan = ComposedFaultPlan(seed=7, specs=[
    ...     FaultSpec("store.client", "stall", at=2, arg=0.2),
    ...     FaultSpec("elastic.heartbeat", "kill", at=3, count=-1,
    ...               match="nodeB"),
    ...     FaultSpec("checkpoint.shard", "bitflip", arg=4),
    ...     FaultSpec("fleet.replica_kill", "kill", at=5, count=1)])
    >>> with plan:
    ...     ...                       # all four sites armed at once
    >>> plan.fired()                  # {site: count} — prove composition
    """

    def __init__(self, seed: int = 0, specs: Sequence[FaultSpec] = ()):
        super().__init__(seed, specs)
        self._spec_rngs = {
            id(s): random.Random(f"{self.seed}:{i}:{s.site}:{s.action}")
            for i, s in enumerate(self.specs)}

    def rng_for(self, spec: FaultSpec) -> random.Random:
        return self._spec_rngs.get(id(spec), self.rng)


_ACTIVE: Optional[FaultPlan] = None


def active_plan() -> Optional[FaultPlan]:
    return _ACTIVE


def maybe_inject(site: str, detail: str = "") -> None:
    """Control-flow hook: no-op without a plan; otherwise sleep/raise per
    the specs due at this event."""
    plan = _ACTIVE
    if plan is None:
        return
    for s in plan.fire(site, detail):
        if s.action in ("stall", "delay"):
            time.sleep(s.arg)
        elif s.action == "kill":
            raise FaultInjected(
                f"fault injected: kill at {site} ({detail})")
        elif s.action == "error":
            raise RuntimeError(f"fault injected: error at {site} ({detail})")
        # data actions at a control-only site are ignored


def corrupt(site: str, detail: str, data: bytes) -> bytes:
    """Data hook: return ``data`` with any due data faults applied."""
    plan = _ACTIVE
    if plan is None:
        return data
    for s in plan.fire(site, detail):
        if s.action == "truncate":
            n = int(s.arg) or max(1, len(data) // 2)
            data = data[: max(0, len(data) - n)]
        elif s.action == "bitflip":
            buf = bytearray(data)
            nbits = int(s.arg) or 1
            rng = plan.rng_for(s)
            # flip bits in the middle half of the payload: past container
            # headers, before trailing indexes — the silent-corruption zone
            lo, hi = len(buf) // 4, max(len(buf) // 4 + 1, (3 * len(buf)) // 4)
            for _ in range(nbits):
                pos = rng.randrange(lo, hi)
                buf[pos] ^= 1 << rng.randrange(8)
            data = bytes(buf)
        elif s.action == "garbage":
            data = bytes(plan.rng_for(s).getrandbits(8)
                         for _ in range(len(data)))
        elif s.action in ("stall", "delay"):
            time.sleep(s.arg)
        elif s.action == "kill":
            raise FaultInjected(
                f"fault injected: kill at {site} ({detail})")
        elif s.action == "error":
            raise RuntimeError(f"fault injected: error at {site} ({detail})")
    return data


def wire_faults(site: str, detail: str = "") -> List[FaultSpec]:
    """Transport hook (``net.connect``/``net.send``/``net.recv``): return
    the specs due at this wire event. The ChaosTransport interprets the
    actions itself — several (``drop``, ``duplicate``, ``torn``,
    ``blackhole``) need frame-level context a byte hook cannot express
    (suppress a send, re-deliver, ship a prefix, poison a peer). No plan
    -> empty list (one global read)."""
    plan = _ACTIVE
    if plan is None:
        return []
    return plan.fire(site, detail)


def resource_hold(site: str, detail: str = "") -> int:
    """Resource hook: number of pool units (serving KV blocks) the due
    ``exhaust`` specs remove from circulation at this event — seeded,
    deterministic pool exhaustion (``serving.block_pool`` site, consulted
    by the serving engine's admission path). No plan -> 0."""
    plan = _ACTIVE
    if plan is None:
        return 0
    total = 0
    for s in plan.fire(site, detail):
        if s.action == "exhaust":
            total += max(0, int(s.arg))
    return total


def device_loss(detail: str = "") -> int:
    """Device-loss hook: number of mesh devices the due ``lose`` specs
    remove from the engine's tp device group at this event — seeded,
    step-indexed device failure (``device.loss`` site, consulted at the
    top of every serving engine step alongside ``serving.step``). The
    sharded engine turns a non-zero return into :class:`MeshDegraded`
    (PT-SRV-008) so the elastic ServingSupervisor can reshard-and-resume
    at the widest surviving width. No plan -> 0 (one global read)."""
    plan = _ACTIVE
    if plan is None:
        return 0
    total = 0
    for s in plan.fire("device.loss", detail):
        if s.action == "lose":
            total += max(0, int(s.arg))
    return total


def numeric_inject_code(detail: str = "") -> int:
    """Numeric hook consulted by the guarded Engine step, once per step.

    Resolves the ``numeric.step`` site's due specs to an in-graph injection
    code (framework.numeric_guard INJECT_*): ``nan_grad`` -> 1 poisons every
    gradient with NaN, ``loss_spike`` -> 2 scales the loss (and therefore
    the gradients) by SPIKE_INJECT_FACTOR *inside* the differentiated
    function. The code rides into jit as a traced scalar — injection never
    recompiles and is observable only through the health word, exactly like
    a real anomaly. No plan -> 0 (one global read)."""
    plan = _ACTIVE
    if plan is None:
        return 0
    for s in plan.fire("numeric.step", detail):
        if s.action == "nan_grad":
            return 1
        if s.action == "loss_spike":
            return 2
    return 0


def poison_arrays(detail, arrays):
    """Data-plane numeric hook: apply due ``poison_batch`` specs to a host
    batch (tuple of numpy arrays) before it ships to the device.

    NaNs ``arg`` seeded positions (default 1%% of elements, at least one)
    in each floating array — integer arrays (token ids) pass through
    untouched. Returns the batch unchanged when no plan is installed."""
    plan = _ACTIVE
    if plan is None:
        return arrays
    due = [s for s in plan.fire("data.batch", str(detail))
           if s.action == "poison_batch"]
    if not due:
        return arrays
    import numpy as np

    out = []
    for a in arrays:
        a = np.asarray(a)
        if not np.issubdtype(a.dtype, np.floating) or a.size == 0:
            out.append(a)
            continue
        a = np.array(a, copy=True)
        flat = a.reshape(-1)
        for s in due:
            n = int(s.arg) or max(1, flat.size // 100)
            rng = plan.rng_for(s)
            for _ in range(n):
                flat[rng.randrange(flat.size)] = np.nan
        out.append(a)
    return tuple(out)
