"""ResilientTrainer — elastic auto-resume training loop.

Reference semantics (fleet/elastic/manager.py:125): fault tolerance =
"restart from checkpoint between min/max nranks". This trainer implements
that contract end to end on the TPU-native stack:

- checkpoints the Engine state every ``save_every`` steps (atomic,
  checksum-verified shards via distributed.checkpoint; periodic saves go
  through ``async_save`` and are *committed* — the LATEST pointer flipped —
  only after ``wait_async_save`` proves the shards are durable, so a crash
  mid-save can never tear the resume point. The pointer itself is
  generation-fenced (``checkpoint/latest.py``): each trainer claims a
  monotonic token at construction, so a zombie writer from before an
  elastic shrink gets a typed :class:`StaleGenerationError` instead of
  rewinding the job);
- watches an :class:`~paddle_tpu.distributed.fleet.elastic.ElasticManager`
  for scale events. A peer loss detected cleanly triggers save → rebuild
  the Engine over the surviving nodes (the caller's ``build_engine``
  chooses the new mesh) → reload → resume at the recorded step. A peer
  loss that first surfaces as a *step exception* (collective timeout, store
  EOF) takes the same path minus the save — the in-flight state is suspect,
  so training resumes from the last durable checkpoint;
- bounds disk usage by keeping the newest ``keep`` checkpoints;
- consumes the engine's numeric :class:`GuardPolicy` (PR-3,
  docs/NUMERIC_GUARD.md): when ``build_engine`` returns an Engine with
  ``guard=GuardPolicy(...)``, every step's on-device health word is routed
  through a :class:`NumericWatchdog` — SKIP_STEP steps were already
  zero-applied in-graph (moments untouched, step counter advanced) and are
  counted against the skip window; ROLLBACK restores the last committed
  checkpoint from the same ring, deterministically re-seeds (the builder
  re-runs), re-warms LR per the policy, and replays; ABORT raises
  :class:`NumericAnomalyError`. Offending batches are captured to
  ``ckpt_dir/badbatch/`` for ``tools/replay_batch.py``.

The loop is deliberately synchronous and host-driven: recovery decisions
are control-plane, and one decision per step costs nothing next to a fused
train step.
"""

from __future__ import annotations

import os
import shutil
from typing import Callable, List, Optional

__all__ = ["ResilientTrainer"]


class ResilientTrainer:
    """Auto-resuming training driver.

    Args:
        build_engine: ``(alive_nodes: List[str]) -> Engine`` — builds the
            model + Engine for the given surviving node set (the caller maps
            nodes to a mesh; on a scale-in it returns an Engine over the
            smaller mesh and ``load_state_dict`` reshards the checkpoint
            onto it).
        ckpt_dir: checkpoint root; each save lands in ``step_<n>/``.
        elastic: optional ElasticManager (already ``start()``-ed); when
            None the trainer still checkpoints/resumes but never reshards.
        save_every: checkpoint cadence in steps.
        keep: how many newest checkpoints to retain.
        max_restarts: scale events tolerated before giving up.
        async_save: write periodic checkpoints in the background (the
            pre-reshard and final saves are always synchronous).
    """

    def __init__(self, build_engine: Callable, ckpt_dir: str, *,
                 elastic=None, save_every: int = 10, keep: int = 3,
                 max_restarts: int = 3, async_save: bool = True):
        self.build_engine = build_engine
        self.ckpt_dir = str(ckpt_dir)
        self.elastic = elastic
        self.save_every = max(1, int(save_every))
        self.keep = max(1, int(keep))
        self.max_restarts = int(max_restarts)
        self.async_save = bool(async_save)
        self.restarts = 0
        self.resumed_at: List[int] = []
        self.numeric_rollbacks = 0
        self.rollback_at: List[int] = []
        self._pending_commit: Optional[int] = None
        os.makedirs(self.ckpt_dir, exist_ok=True)
        # fence token: strictly above whatever is committed, so a zombie
        # trainer from before a shrink/restart can never move LATEST
        # backwards (PT-CKPT-005, checkpoint/latest.py)
        from ..checkpoint.latest import claim_generation

        self.generation = claim_generation(self.ckpt_dir)

    # -- checkpoint bookkeeping -------------------------------------------
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.ckpt_dir, f"step_{step:08d}")

    def _recorded_steps(self) -> List[int]:
        out = []
        for name in os.listdir(self.ckpt_dir):
            if name.startswith("step_"):
                try:
                    out.append(int(name[len("step_"):]))
                except ValueError:
                    pass
        return sorted(out)

    def _write_latest(self, step: int) -> None:
        from ..checkpoint.latest import commit_latest

        commit_latest(self.ckpt_dir, step, self.generation)

    def latest_step(self) -> Optional[int]:
        from ..checkpoint.latest import read_latest

        rec = read_latest(self.ckpt_dir)
        return rec[0] if rec is not None else None

    def save(self, engine, step: int, sync: bool = False) -> None:
        """Checkpoint the engine at ``step``. Async saves are committed (the
        LATEST pointer moved) by the next :meth:`commit` — pointer and data
        can never disagree."""
        from ..checkpoint import save_state_dict

        self.commit()                       # previous async save, if any
        path = self._step_dir(step)
        use_async = self.async_save and not sync
        save_state_dict(engine.state_dict(), path, async_save=use_async)
        if use_async:
            self._pending_commit = step
        else:
            self._write_latest(step)
            self._gc()

    def commit(self) -> None:
        """Flush any in-flight async save and move the LATEST pointer. The
        pending step is dropped BEFORE the flush: if ``wait_async_save``
        raises (a shard writer died mid-flush), the pointer move is
        abandoned for good — a later commit must not find the queue
        drained and flip LATEST to the torn step."""
        if self._pending_commit is None:
            return
        from ..checkpoint import wait_async_save

        step, self._pending_commit = self._pending_commit, None
        wait_async_save()
        self._write_latest(step)
        self._gc()

    def _gc(self) -> None:
        latest = self.latest_step()
        steps = self._recorded_steps()
        doomed = [s for s in steps[: -self.keep] if s != latest]
        for s in doomed:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)

    # -- resume ------------------------------------------------------------
    def resume(self, engine) -> int:
        """Load the newest *valid* checkpoint into ``engine`` (reshard-on-
        load under the engine's current mesh); returns the step to resume
        at (0 when no checkpoint exists). A corrupt newest checkpoint falls
        back to the next-newest — PT-CKPT detection, not silent load."""
        import numpy as np

        from ..checkpoint import CheckpointCorruptionError, load_state_dict

        latest = self.latest_step()
        candidates = [s for s in reversed(self._recorded_steps())
                      if latest is None or s <= latest]
        for step in candidates:
            sd = engine.state_dict()
            try:
                load_state_dict(sd, self._step_dir(step))
            except CheckpointCorruptionError:
                continue                    # named in the error; try older
            except FileNotFoundError:
                continue                    # torn dir (no metadata yet)
            engine.set_state_dict(sd)
            return int(np.asarray(sd["step"]))
        return 0

    # -- elastic loop ------------------------------------------------------
    def _alive(self) -> List[str]:
        if self.elastic is None:
            return ["local"]
        alive = self.elastic.alive_peers()
        # self always counts: our own heartbeat may simply not have landed
        if self.elastic.node_id not in alive:
            alive = sorted(set(alive) | {self.elastic.node_id})
        return alive

    def _scale_event(self) -> bool:
        if self.elastic is None:
            return False
        try:
            return self.elastic.peers_changed()
        except Exception:
            # liveness poll itself hit a (possibly transient) store failure:
            # not evidence of a scale event — if the store is really gone
            # the training step will surface it on the recovery path
            return False

    def _await_scale_event(self) -> bool:
        """After a step exception: was it a dying peer? A transport failure
        surfaces in O(retry budget) but heartbeat staleness needs up to
        ``ttl`` to become visible, so re-poll across that window before
        concluding the failure was not elastic. A transient blip whose
        peers stay healthy returns False and the exception propagates."""
        import time

        if self.elastic is None:
            return False
        deadline = time.monotonic() + float(self.elastic.ttl) + 1.0
        while True:
            if self._scale_event():     # poll errors read as "not yet"
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(min(0.2, max(0.02, self.elastic.interval / 2)))

    def _reshard(self, save_from=None, step: Optional[int] = None):
        """Rebuild over the survivors and resume from checkpoint."""
        if self.restarts >= self.max_restarts:
            raise RuntimeError(
                f"elastic restart budget exhausted ({self.max_restarts})")
        if save_from is not None and step is not None:
            self.save(save_from, step, sync=True)   # state is good: persist
        else:
            self.commit()                   # keep only durable progress
        alive = self._alive()
        if self.elastic is not None:
            self.elastic.reset_expected(alive)
        engine = self.build_engine(alive)
        resumed = self.resume(engine)
        self.restarts += 1
        self.resumed_at.append(resumed)
        return engine, resumed

    def fit(self, data_fn: Callable, steps: int, *, shard: bool = True):
        """Train to ``steps`` with auto-resume.

        ``data_fn(step) -> (inputs, labels)`` must be deterministic in
        ``step`` — replayed steps after a resume then reproduce the exact
        uninterrupted trajectory. Returns ``{"engine", "losses", "restarts",
        "resumed_at", "final_step"}``.
        """
        from .faults import poison_arrays

        engine = self.build_engine(self._alive())
        step = self.resume(engine)
        losses = {}
        watchdog, recorder = self._arm_guard(engine)
        while step < steps:
            if self._scale_event():
                engine, step = self._reshard(save_from=engine, step=step)
                continue
            try:
                ids, lbl = poison_arrays(step, data_fn(step))
                batch = (engine.shard_batch(ids, lbl)
                         if shard and engine.mesh is not None else (ids, lbl))
                if watchdog is not None:
                    engine.lr_scale = watchdog.lr_scale(step)
                loss = engine.step(*batch)
            except Exception:
                # a dead peer often surfaces as a collective/store failure
                # BEFORE the heartbeat scan sees it — wait out the ttl
                # window for the scale event, then take the same recovery,
                # minus the save (in-flight state is suspect): resume from
                # the last durable checkpoint.
                if self._await_scale_event():
                    engine, step = self._reshard()
                    continue
                raise
            if watchdog is not None:
                word = (int(engine.last_health)
                        if engine.last_health is not None else 0)
                if word:
                    decision = watchdog.observe(step + 1, word)
                    if recorder is not None:
                        recorder.record(
                            step + 1, word,
                            {"input_ids": ids, "labels": lbl},
                            extra={"decision": decision,
                                   "lr_scale": float(engine.lr_scale)})
                    if decision == "abort":
                        from ...framework.numeric_guard import \
                            NumericAnomalyError

                        raise NumericAnomalyError(
                            word, step=step + 1,
                            detail="guard budgets exhausted"
                            if engine.guard.action != "abort" else "")
                    if decision == "rollback":
                        # the anomalous update was zero-applied in-graph,
                        # but a streak (or explicit policy) means the
                        # trajectory is suspect: restore the last COMMITTED
                        # ring entry, re-seed via the builder, re-warm LR.
                        self.commit()
                        engine = self.build_engine(self._alive())
                        step = self.resume(engine)
                        watchdog.note_rollback(step)
                        self.numeric_rollbacks += 1
                        self.rollback_at.append(step)
                        continue
                    # "warn" applied the update; "skip_step" zero-applied —
                    # either way the step counter advances below.
            step += 1
            losses[step] = float(loss)
            if step % self.save_every == 0 and step < steps:
                self.save(engine, step)
        self.save(engine, steps, sync=True)
        return {"engine": engine, "losses": losses, "restarts": self.restarts,
                "resumed_at": list(self.resumed_at), "final_step": step,
                "numeric_rollbacks": self.numeric_rollbacks,
                "rollback_at": list(self.rollback_at),
                "numeric_skips": (list(watchdog.skipped_steps)
                                  if watchdog is not None else []),
                "numeric_events": (list(watchdog.events)
                                   if watchdog is not None else [])}

    def _arm_guard(self, engine):
        """Build the watchdog + bad-batch recorder when the engine carries a
        numeric GuardPolicy (guard state survives engine rebuilds on the
        watchdog, not the engine)."""
        guard = getattr(engine, "guard", None)
        if guard is None:
            return None, None
        from ...framework.numeric_guard import BadBatchRecorder
        from .watchdog import NumericWatchdog

        recorder = (BadBatchRecorder(os.path.join(self.ckpt_dir, "badbatch"))
                    if guard.record_bad_batches else None)
        return NumericWatchdog(guard), recorder
