"""paddle_tpu.distributed.resilience — fault injection, retry, auto-resume.

The fault-tolerance layer spanning the store (communication/store.py), the
elastic manager (fleet/elastic/manager.py), distributed checkpointing
(distributed/checkpoint/), and the serving engine (inference/serving.py).
See docs/RESILIENCE.md for the fault model, the injection-site catalogue,
and the PT-RETRY / PT-CKPT diagnostic codes.

Import discipline: this package sits *below* those subsystems (they import
it at module load), so ``faults``/``retry`` are stdlib-only; the trainer —
which pulls in the auto-parallel Engine stack — loads lazily.
"""

from .faults import (  # noqa: F401
    ComposedFaultPlan,
    FaultInjected,
    FaultPlan,
    FaultSpec,
    active_plan,
    corrupt,
    maybe_inject,
    numeric_inject_code,
    poison_arrays,
)
from .retry import (  # noqa: F401
    DEFAULT_POLICY,
    RetryError,
    RetryPolicy,
    reset_retry_stats,
    retries_disabled,
    retry_call,
    retry_stats,
)
# eager is safe here: the watchdogs consume framework.numeric_guard /
# stdlib threading only — no jax/Engine import at load (unlike the trainer)
from .watchdog import NumericWatchdog, StepWatchdog  # noqa: F401

__all__ = [
    "ComposedFaultPlan", "FaultInjected", "FaultPlan", "FaultSpec",
    "active_plan", "corrupt",
    "maybe_inject", "numeric_inject_code", "poison_arrays",
    "DEFAULT_POLICY", "RetryError", "RetryPolicy",
    "retries_disabled", "retry_call", "retry_stats", "reset_retry_stats",
    "ResilientTrainer", "NumericWatchdog", "StepWatchdog",
    "CheckpointCorruptionError", "EngineSaturated",
    "CheckpointPublisher", "StaleGenerationError", "lifecycle_stats",
    "reset_lifecycle_stats", "set_lifecycle_phase",
]


def __getattr__(name):
    # lazy: these pull in jax / the Engine stack (or sit beside modules
    # that do), which would cycle with distributed/__init__ if imported
    # eagerly here
    if name == "ResilientTrainer":
        from .trainer import ResilientTrainer

        return ResilientTrainer
    if name == "CheckpointCorruptionError":
        from ..checkpoint.integrity import CheckpointCorruptionError

        return CheckpointCorruptionError
    if name == "StaleGenerationError":
        from ..checkpoint.latest import StaleGenerationError

        return StaleGenerationError
    if name == "EngineSaturated":
        from ...inference.serving import EngineSaturated

        return EngineSaturated
    if name in ("CheckpointPublisher", "lifecycle_stats",
                "reset_lifecycle_stats", "set_lifecycle_phase"):
        from . import lifecycle

        return getattr(lifecycle, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
