"""paddle_tpu.quantization — QAT / PTQ.

Parity anchors: python/paddle/quantization (QuantConfig quanter mapping, QAT
`qat.py`, PTQ `ptq.py`, observers under quantization/observers, quanted layers
under nn/quant) in the reference.

TPU-native design:
  - fake-quant in QAT uses the straight-through estimator expressed as
    ``x + stop_gradient(q(x) - x)`` — jax autodiff gives the STE gradient for
    free, no custom backward registration.
  - converted (deployment) linears run a REAL int8×int8→int32 matmul via
    ``lax.dot_general(..., preferred_element_type=int32)``, which XLA maps to
    the MXU's low-precision path, then dequantize by the per-channel scales.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core.op_registry import apply_fn
from ..core.tensor import Tensor, unwrap
from ..nn.layer.layers import Layer
from .. import nn
# int8 paged-KV block format (docs/SERVING.md "int8 KV cache"): the serving
# pools' quantized layout — int8 pages + per-(page, head) absmax scales,
# same scale convention as PerChannelAbsmaxObserver / ConvertedLinear
# (scale == absmax, qmax = 2^(bits-1) - 1). Lives beside the paged kernels
# (ops/paged_attention.py) and is re-exported here as the quantization-
# facing API surface; opt in via serving.KVCacheConfig(dtype="int8").
from ..ops.paged_attention import (KV_QMAX, QuantizedKVPool,  # noqa: F401
                                   dequantize_kv, kv_absmax, quantize_kv)

__all__ = [
    "AbsmaxObserver", "MovingAverageAbsmaxObserver", "PerChannelAbsmaxObserver",
    "QuantConfig", "QAT", "PTQ", "QuantedLinear", "ConvertedLinear",
    "fake_quant",
    "KV_QMAX", "QuantizedKVPool", "quantize_kv", "dequantize_kv", "kv_absmax",
]


# ---------------------------------------------------------------------------
# observers (reference: python/paddle/quantization/observers/abs_max.py)
# ---------------------------------------------------------------------------

class AbsmaxObserver:
    """Running max of |x| — per-tensor scale."""

    def __init__(self, quant_bits: int = 8):
        self.quant_bits = quant_bits
        self._absmax = 0.0

    def sample(self, x):
        v = float(jnp.max(jnp.abs(x._data if isinstance(x, Tensor) else x)))
        self._absmax = max(self._absmax, v)

    def scale(self) -> float:
        return self._absmax if self._absmax > 0 else 1.0

    def qmax(self) -> int:
        return 2 ** (self.quant_bits - 1) - 1


class MovingAverageAbsmaxObserver(AbsmaxObserver):
    """EMA of per-batch absmax (reference: moving_average_abs_max quanter)."""

    def __init__(self, quant_bits: int = 8, moving_rate: float = 0.9):
        super().__init__(quant_bits)
        self.moving_rate = moving_rate
        self._initialized = False

    def sample(self, x):
        v = float(jnp.max(jnp.abs(x._data if isinstance(x, Tensor) else x)))
        if not self._initialized:
            self._absmax = v
            self._initialized = True
        else:
            self._absmax = self.moving_rate * self._absmax + (1 - self.moving_rate) * v


class PerChannelAbsmaxObserver:
    """Per-output-channel absmax — for weights (reference: channel_wise_abs_max)."""

    def __init__(self, quant_bits: int = 8, channel_axis: int = -1):
        self.quant_bits = quant_bits
        self.channel_axis = channel_axis
        self._absmax = None

    def sample(self, x):
        a = jnp.abs(x._data if isinstance(x, Tensor) else x)
        axes = tuple(i for i in range(a.ndim)
                     if i != self.channel_axis % a.ndim)
        v = jnp.max(a, axis=axes)
        self._absmax = v if self._absmax is None else jnp.maximum(self._absmax, v)

    def scale(self):
        if self._absmax is None:
            return jnp.ones((1,), jnp.float32)
        return jnp.maximum(self._absmax, 1e-8)

    def qmax(self) -> int:
        return 2 ** (self.quant_bits - 1) - 1


# ---------------------------------------------------------------------------
# fake quant (STE)
# ---------------------------------------------------------------------------

def _fake_quant_kernel(a, scale, qmax):
    s = scale / qmax
    q = jnp.clip(jnp.round(a / s), -qmax, qmax) * s
    # straight-through estimator: forward q, backward identity
    return a + jax.lax.stop_gradient(q - a)


def fake_quant(x, scale, quant_bits: int = 8):
    """Simulated quantization with STE gradient."""
    qmax = 2 ** (quant_bits - 1) - 1
    scale = jnp.asarray(scale, jnp.float32)
    return apply_fn("fake_quantize", _fake_quant_kernel, x, scale=scale, qmax=qmax)


# ---------------------------------------------------------------------------
# quanted layers
# ---------------------------------------------------------------------------

class QuantedLinear(Layer):
    """Linear with fake-quantized weight (per-channel) and activation
    (per-tensor, observer-tracked). Reference: nn/quant/qat/linear.py."""

    def __init__(self, linear, activation_observer=None, weight_bits: int = 8,
                 act_bits: int = 8):
        super().__init__()
        self.weight = linear.weight
        self.bias = linear.bias
        self._w_obs = PerChannelAbsmaxObserver(weight_bits, channel_axis=-1)
        self._a_obs = activation_observer or MovingAverageAbsmaxObserver(act_bits)
        self._act_bits = act_bits
        self._weight_bits = weight_bits
        self._calibrating = False  # PTQ: sample observers while model is eval()

    def forward(self, x):
        if self.training or self._calibrating:
            self._a_obs.sample(x)
        xq = fake_quant(x, self._a_obs.scale(), self._act_bits)
        self._w_obs.sample(self.weight)
        wq = fake_quant(self.weight, self._w_obs.scale(), self._weight_bits)
        out = nn.functional.linear(xq, wq, self.bias)
        return out


class ConvertedLinear(Layer):
    """Deployment linear: int8 weights + real int8 matmul
    (reference: the converted inference program after PTQ/QAT convert)."""

    def __init__(self, linear, w_scale, a_scale: float, act_bits: int = 8,
                 weight_bits: int = 8):
        super().__init__()
        w = linear.weight._data.astype(jnp.float32)
        self._w_qmax = 2 ** (weight_bits - 1) - 1
        self._a_qmax = 2 ** (act_bits - 1) - 1
        self._w_scale = jnp.asarray(w_scale, jnp.float32)  # [out_features]
        self._a_scale = float(a_scale)
        wq = jnp.clip(jnp.round(w / (self._w_scale / self._w_qmax)),
                      -self._w_qmax, self._w_qmax).astype(jnp.int8)
        self.register_buffer("qweight", Tensor(wq))
        self.bias = linear.bias

    def forward(self, x):
        def fn(a, qw, b=None):
            a_s = self._a_scale / self._a_qmax
            aq = jnp.clip(jnp.round(a.astype(jnp.float32) / a_s),
                          -self._a_qmax, self._a_qmax).astype(jnp.int8)
            # int8 x int8 -> int32 on the MXU
            acc = jax.lax.dot_general(
                aq, qw, (((aq.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            out = acc.astype(jnp.float32) * (a_s * (self._w_scale / self._w_qmax))
            if b is not None:
                out = out + b
            return out.astype(a.dtype)

        if self.bias is not None:
            return apply_fn("quantized_linear", fn, x, self.qweight, self.bias)
        return apply_fn("quantized_linear", fn, x, self.qweight)


# ---------------------------------------------------------------------------
# QuantConfig / QAT / PTQ drivers
# ---------------------------------------------------------------------------

class QuantedConv2D(Layer):
    """QAT conv: fake-quant per-channel weights + per-tensor activations in
    forward (straight-through estimator in backward) — the conv analogue of
    QuantedLinear. Reference: nn/quant/qat/conv.py."""

    def __init__(self, conv, activation_observer=None, weight_bits: int = 8,
                 act_bits: int = 8):
        super().__init__()
        self._conv = conv
        self.weight = conv.weight
        self.bias = conv.bias
        # out-channel axis 0 of the OIHW weight layout
        self._w_obs = PerChannelAbsmaxObserver(weight_bits, channel_axis=0)
        self._a_obs = activation_observer or MovingAverageAbsmaxObserver(act_bits)
        self._w_bits, self._a_bits = weight_bits, act_bits
        self._calibrating = False  # matches QuantedLinear: eval() is stable

    def forward(self, x):
        if self._calibrating or self.training:
            self._a_obs.sample(unwrap(x))
            self._w_obs.sample(self.weight._data)
        xq = fake_quant(x, self._a_obs.scale(), self._a_bits)
        # per-out-channel scale broadcasts over the OIHW trailing dims
        w_scale = jnp.asarray(self._w_obs.scale()).reshape(
            (-1,) + (1,) * (self.weight._data.ndim - 1))
        wq = fake_quant(self.weight, w_scale, self._w_bits)
        c = self._conv
        return nn.functional.conv2d(xq, wq, self.bias, c._stride, c._padding,
                                    c._dilation, c._groups, c._data_format)


class ConvertedConv2D(Layer):
    """Deployment conv: int8-stored per-channel weights, dequantized at the
    conv input (XLA fuses the dequant into the conv). Unlike the linear case
    there is no profitable raw int8xint8 conv on the MXU, so storage is
    quantized and compute is bf16/f32 — the reference's onednn int8 conv plays
    the same storage-vs-compute trade on CPU."""

    def __init__(self, conv, w_scale, a_scale, act_bits: int = 8,
                 weight_bits: int = 8):
        super().__init__()
        inner = getattr(conv, "_conv", conv)
        # keep only the conv CONFIG, never the live layer — registering it
        # would drag the fp32 weight into parameters()/state_dict(), making
        # the "int8 deployment" bigger than the original
        self._cfg = (inner._stride, inner._padding, inner._dilation,
                     inner._groups, inner._data_format)
        w = conv.weight._data.astype(jnp.float32)
        qmax = 2 ** (weight_bits - 1) - 1
        # observer convention: scale == per-channel absmax; int value is
        # round(w / (absmax / qmax)) — same as ConvertedLinear
        scale = jnp.maximum(jnp.asarray(w_scale, jnp.float32), 1e-8)
        bshape = (-1,) + (1,) * (w.ndim - 1)
        step = (scale / qmax).reshape(bshape)
        self.register_buffer("qweight", Tensor(
            jnp.clip(jnp.round(w / step), -qmax, qmax).astype(jnp.int8)))
        self._w_step = step
        self.bias = inner.bias

    def forward(self, x):
        stride, padding, dilation, groups, data_format = self._cfg

        def fn(a, qw, *b):
            w = qw.astype(a.dtype) * self._w_step.astype(a.dtype)
            return unwrap(nn.functional.conv2d(
                Tensor(a), Tensor(w), Tensor(b[0]) if b else None, stride,
                padding, dilation, groups, data_format))

        args = [x, self.qweight] + ([self.bias] if self.bias is not None else [])
        return apply_fn("quantized_conv2d", fn, *args)


class QuantConfig:
    """Which layers to quantize, with which observers
    (reference: python/paddle/quantization/config.py)."""

    def __init__(self, activation=None, weight=None, quant_bits: int = 8):
        self.activation_factory = activation or (
            lambda: MovingAverageAbsmaxObserver(quant_bits))
        self.weight_bits = quant_bits
        self.act_bits = quant_bits
        self._types = (nn.Linear,)

    def add_type_config(self, layer_types, activation=None, weight=None):
        if not isinstance(layer_types, (list, tuple)):
            layer_types = [layer_types]
        self._types = tuple(set(self._types) | set(layer_types))


def _replace_layers(model: Layer, predicate, build):
    for name, child in list(model.named_children()):
        if predicate(child):
            setattr(model, name, build(child))
        else:
            _replace_layers(child, predicate, build)
    return model


class QAT:
    """Quantization-aware training driver (reference: quantization/qat.py)."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig()

    def quantize(self, model: Layer, inplace: bool = True) -> Layer:
        cfg = self.config
        # validate BEFORE mutating so an error never leaves the model
        # half-quantized
        unsupported = sorted({
            type(l).__name__ for _, l in model.named_sublayers()
            if isinstance(l, cfg._types)
            and not isinstance(l, (nn.Linear, nn.Conv2D))})
        if unsupported:
            raise NotImplementedError(
                f"quantization of {', '.join(unsupported)} is not supported "
                f"yet (Linear and Conv2D — see docs/PARITY.md)")
        if not inplace:
            import copy

            model = copy.deepcopy(model)

        def build(l):
            if isinstance(l, nn.Conv2D):
                return QuantedConv2D(l, cfg.activation_factory(),
                                     cfg.weight_bits, cfg.act_bits)
            return QuantedLinear(l, cfg.activation_factory(),
                                 cfg.weight_bits, cfg.act_bits)

        return _replace_layers(
            model, lambda l: isinstance(l, cfg._types), build)

    def convert(self, model: Layer, inplace: bool = True) -> Layer:
        cfg = self.config
        if not inplace:
            import copy

            model = copy.deepcopy(model)
        def build(l):
            if isinstance(l, QuantedConv2D):
                return ConvertedConv2D(l, l._w_obs.scale(), l._a_obs.scale(),
                                       cfg.act_bits, cfg.weight_bits)
            return ConvertedLinear(l, l._w_obs.scale(), l._a_obs.scale(),
                                   cfg.act_bits, cfg.weight_bits)

        return _replace_layers(
            model,
            lambda l: isinstance(l, (QuantedLinear, QuantedConv2D)),
            build)


class PTQ:
    """Post-training quantization: insert observers, run calibration batches,
    convert (reference: quantization/ptq.py)."""

    def __init__(self, config: Optional[QuantConfig] = None):
        self.config = config or QuantConfig(
            activation=lambda: AbsmaxObserver(8))
        self._qat = QAT(self.config)

    def quantize(self, model: Layer, inplace: bool = True) -> Layer:
        # calibration happens in eval() mode (dropout etc. must be OFF so the
        # observers see inference-time activations — reference ptq.py does the
        # same); only the quanted layers' observers are switched on
        q = self._qat.quantize(model, inplace)
        q.eval()
        for _, layer in q.named_sublayers(include_self=True):
            if isinstance(layer, (QuantedLinear, QuantedConv2D)):
                layer._calibrating = True
        return q

    def convert(self, model: Layer, inplace: bool = True) -> Layer:
        model.eval()
        for _, layer in model.named_sublayers(include_self=True):
            if isinstance(layer, (QuantedLinear, QuantedConv2D)):
                layer._calibrating = False
        return self._qat.convert(model, inplace)
