"""paddle_tpu.signal — stft/istft (reference: python/paddle/signal.py)."""

from __future__ import annotations

import jax.numpy as jnp

from .core.op_registry import apply_fn
from .core.tensor import Tensor, unwrap

__all__ = ["stft", "istft", "frame", "overlap_add"]


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Overlapping frames. axis=-1: [..., n] -> [..., frame_length, num];
    axis=0: [n, ...] -> [num, frame_length, ...] (reference: signal.py frame)."""
    if axis not in (-1, 0):
        raise ValueError("frame supports axis in (-1, 0)")

    def fn(a):
        if axis == 0:
            a = jnp.moveaxis(a, 0, -1)
        n = a.shape[-1]
        num = 1 + (n - frame_length) // hop_length
        idx = (jnp.arange(frame_length)[:, None]
               + hop_length * jnp.arange(num)[None, :])
        out = a[..., idx]  # [..., frame_length, num]
        if axis == 0:
            # -> [num, frame_length, ...]
            out = jnp.moveaxis(jnp.moveaxis(out, -1, 0), -1, 1)
        return out

    return apply_fn("frame", fn, x)


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame. axis=-1: [..., frame_length, num] -> [..., n];
    axis=0: [num, frame_length, ...] -> [n, ...]."""
    if axis not in (-1, 0):
        raise ValueError("overlap_add supports axis in (-1, 0)")

    def fn(a):
        if axis == 0:
            a = jnp.moveaxis(jnp.moveaxis(a, 1, -1), 0, -1)
        fl, num = a.shape[-2], a.shape[-1]
        n = fl + hop_length * (num - 1)
        out = jnp.zeros(a.shape[:-2] + (n,), a.dtype)
        for j in range(num):  # static unroll: num_frames is static under jit
            out = out.at[..., j * hop_length:j * hop_length + fl].add(a[..., j])
        if axis == 0:
            out = jnp.moveaxis(out, -1, 0)
        return out

    return apply_fn("overlap_add", fn, x)


def _pad_window(w, n_fft, win_length):
    lo = (n_fft - win_length) // 2
    return jnp.pad(w, (lo, n_fft - win_length - lo)) if win_length < n_fft else w


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    """Short-time Fourier transform -> [..., n_fft//2+1 or n_fft, num_frames]."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    w = unwrap(window) if window is not None else jnp.ones(win_length)

    def fn(a, w):
        w_p = _pad_window(w, n_fft, win_length)
        if center:
            a = jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(n_fft // 2, n_fft // 2)],
                        mode=pad_mode)
        frames_n = 1 + (a.shape[-1] - n_fft) // hop_length
        idx = (jnp.arange(n_fft)[:, None]
               + hop_length * jnp.arange(frames_n)[None, :])
        fr = a[..., idx] * w_p[:, None]
        spec = (jnp.fft.rfft(fr, axis=-2) if onesided
                else jnp.fft.fft(fr, axis=-2))
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return spec

    if window is not None:
        return apply_fn("stft", fn, x, Tensor(w))
    return apply_fn("stft", lambda a: fn(a, jnp.ones(win_length)), x)


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False,
          name=None):
    """Inverse STFT with window-envelope normalization (NOLA)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    w = unwrap(window) if window is not None else jnp.ones(win_length)

    def fn(spec, w):
        w_p = _pad_window(w, n_fft, win_length)
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        fr = (jnp.fft.irfft(spec, n=n_fft, axis=-2) if onesided
              else jnp.fft.ifft(spec, axis=-2).real)
        fr = fr * w_p[:, None]
        num = fr.shape[-1]
        n = n_fft + hop_length * (num - 1)
        out = jnp.zeros(fr.shape[:-2] + (n,), fr.dtype)
        env = jnp.zeros((n,), fr.dtype)
        for j in range(num):
            sl = slice(j * hop_length, j * hop_length + n_fft)
            out = out.at[..., sl].add(fr[..., j])
            env = env.at[sl].add(w_p * w_p)
        out = out / jnp.maximum(env, 1e-11)
        if center:
            out = out[..., n_fft // 2: n - n_fft // 2]
        if length is not None:
            out = out[..., :length]
        return out

    return apply_fn("istft", fn, x, Tensor(w))
