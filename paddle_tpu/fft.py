"""paddle_tpu.fft (reference: python/paddle/fft.py — ~20 public functions over
phi fft kernels). TPU-native: jnp.fft lowers to XLA's FFT HLO."""

from __future__ import annotations

import jax.numpy as jnp

from .core.op_registry import apply_fn
from .core.tensor import Tensor

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2",
    "fftn", "ifftn", "rfftn", "irfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def _wrap1(op_name, fn):
    def f(x, n=None, axis=-1, norm="backward", name=None):
        return apply_fn(op_name, lambda a: fn(a, n=n, axis=axis, norm=norm), x)

    f.__name__ = op_name
    return f


def _wrap2(op_name, fn):
    def f(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return apply_fn(op_name, lambda a: fn(a, s=s, axes=axes, norm=norm), x)

    f.__name__ = op_name
    return f


def _wrapn(op_name, fn):
    def f(x, s=None, axes=None, norm="backward", name=None):
        return apply_fn(op_name, lambda a: fn(a, s=s, axes=axes, norm=norm), x)

    f.__name__ = op_name
    return f


fft = _wrap1("fft", jnp.fft.fft)
ifft = _wrap1("ifft", jnp.fft.ifft)
rfft = _wrap1("rfft", jnp.fft.rfft)
irfft = _wrap1("irfft", jnp.fft.irfft)
hfft = _wrap1("hfft", jnp.fft.hfft)
ihfft = _wrap1("ihfft", jnp.fft.ihfft)
fft2 = _wrap2("fft2", jnp.fft.fft2)
ifft2 = _wrap2("ifft2", jnp.fft.ifft2)
rfft2 = _wrap2("rfft2", jnp.fft.rfft2)
irfft2 = _wrap2("irfft2", jnp.fft.irfft2)
fftn = _wrapn("fftn", jnp.fft.fftn)
ifftn = _wrapn("ifftn", jnp.fft.ifftn)
rfftn = _wrapn("rfftn", jnp.fft.rfftn)
irfftn = _wrapn("irfftn", jnp.fft.irfftn)


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(int(n), d=float(d)).astype(dtype or "float32"))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(int(n), d=float(d)).astype(dtype or "float32"))


def fftshift(x, axes=None, name=None):
    return apply_fn("fftshift", lambda a: jnp.fft.fftshift(a, axes=axes), x)


def ifftshift(x, axes=None, name=None):
    return apply_fn("ifftshift", lambda a: jnp.fft.ifftshift(a, axes=axes), x)
