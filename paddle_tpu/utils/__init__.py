"""paddle_tpu.utils (reference: python/paddle/utils)."""

from . import cpp_extension  # noqa: F401
from .custom_op import custom_op  # noqa: F401

__all__ = ["cpp_extension", "custom_op", "run_check", "try_import"]


def try_import(name: str):
    import importlib

    try:
        return importlib.import_module(name)
    except ImportError:
        return None


def run_check() -> None:
    """Sanity-check the installation end-to-end (reference:
    utils/install_check.py:215 run_check): train a tiny linear model for a
    few steps on the active backend and report the device.
    """
    import jax
    import numpy as np

    import paddle_tpu as paddle

    print("Running verify paddle_tpu program ... ")
    devices = jax.devices()
    dev = devices[0]
    # a diagnostic must not clobber the process RNG stream: save + restore
    rng_state = paddle.get_rng_state()
    paddle.seed(0)
    lin = paddle.nn.Linear(4, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((16, 4)).astype(np.float32))
    y = paddle.to_tensor(rng.standard_normal((16, 1)).astype(np.float32))
    first = last = None
    for _ in range(5):
        loss = ((lin(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        last = float(loss)
        first = first if first is not None else last
    if not (np.isfinite(last) and last < first):
        raise RuntimeError(
            f"verification train loop failed to improve: {first} -> {last}")
    paddle.set_rng_state(rng_state)
    kind = getattr(dev, "device_kind", dev.platform)
    n = len(devices)
    extra = "" if n == 1 else f" ({n} devices visible; exercised device 0)"
    print(f"paddle_tpu works well on 1 {kind}{extra}.")
    print("paddle_tpu is installed successfully! Let's start deep learning "
          "with paddle_tpu now.")
