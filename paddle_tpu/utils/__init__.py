"""paddle_tpu.utils (reference: python/paddle/utils)."""

from . import cpp_extension  # noqa: F401
from .custom_op import custom_op  # noqa: F401

__all__ = ["cpp_extension", "custom_op"]


def try_import(name: str):
    import importlib

    try:
        return importlib.import_module(name)
    except ImportError:
        return None
