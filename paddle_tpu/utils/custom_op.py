"""Python/Pallas custom ops — the TPU-native custom-kernel story.

Parity anchor: the reference's PD_BUILD_OP C++ custom operator
(/root/reference/paddle/fluid/framework/custom_operator.cc) whose point is
"add an op without rebuilding the framework". On TPU the fast path for a user
kernel is a Pallas kernel or a jax-traceable function, not C++ — this
decorator registers either into the one op registry so it dispatches with
tape/AMP/static-graph semantics like every built-in op.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax

from ..core import op_registry
from ..core.op_registry import AMP_NEUTRAL, OpDef, apply_fn


def custom_op(name: str, vjp: Optional[Callable] = None,
              amp: str = AMP_NEUTRAL):
    """Register a jax-traceable function (jnp code or a Pallas call) as a
    framework op.

    ``vjp(primals..., cotangent) -> grads...`` if given wires a custom
    backward (the analogue of PD_BUILD_GRAD_OP); otherwise jax autodiff
    differentiates through the function body.

    >>> @custom_op("my_gelu")
    ... def my_gelu(x):
    ...     return 0.5 * x * (1 + jnp.tanh(0.7978845608 * (x + 0.044715 * x**3)))
    >>> y = my_gelu(paddle.to_tensor(...))   # tape/AMP/jit-aware
    """

    def deco(fn):
        kernel = fn
        if vjp is not None:
            wrapped = jax.custom_vjp(fn)

            def fwd(*args):
                return fn(*args), args

            def bwd(saved, cot):
                grads = vjp(*saved, cot)
                return grads if isinstance(grads, tuple) else (grads,)

            wrapped.defvjp(fwd, bwd)
            kernel = wrapped
        op_registry.OPS[name] = OpDef(name, kernel, amp=amp, doc=fn.__doc__ or "")

        def call(*args, **kwargs):
            return apply_fn(name, kernel, *args, **kwargs)

        call.__name__ = name
        call.__doc__ = fn.__doc__
        call._kernel = kernel
        return call

    return deco
