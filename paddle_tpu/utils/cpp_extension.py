"""C++ custom-op extension: compile user C++ at runtime and dispatch it as a
framework op.

Parity anchors: python/paddle/utils/cpp_extension (load/CppExtension/setup JIT
build path) and the C++ registration macro PD_BUILD_OP
(/root/reference/paddle/fluid/framework/custom_operator.cc).

TPU-native contract: XLA owns the device, so arbitrary C++ cannot run ON the
chip — C++ ops execute on the HOST via ``jax.pure_callback`` (one D2H/H2D
round-trip per call, same placement as the reference's custom CPU ops). The
device-speed path for user kernels is ``paddle_tpu.utils.custom_op`` with a
Pallas body. This module is for host-side logic: C++ tokenizers, samplers,
reference kernels, legacy code.

C ABI (replaces PD_BUILD_OP macro):
    extern "C" const char* pt_op_list();       // "relu6,scale2"
    extern "C" void <name>(const float* x, float* y, int64_t n);
    extern "C" void <name>_grad(const float* x, const float* gy,
                                float* gx, int64_t n);   // optional
Elementwise float32 signature; `<name>_grad`, when exported, wires the op's
backward (PD_BUILD_GRAD_OP analogue).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.op_registry import apply_fn

__all__ = ["load", "CppExtensionModule"]

_CACHE_DIR = os.path.join(tempfile.gettempdir(), "paddle_tpu_extensions")


def _build(name: str, sources: Sequence[str], extra_cflags=()) -> str:
    os.makedirs(_CACHE_DIR, exist_ok=True)
    blobs = []
    for s in sources:
        with open(s, "rb") as f:
            blobs.append(f.read())
    tag = hashlib.sha256(b"\0".join(blobs) + repr(extra_cflags).encode()).hexdigest()[:16]
    so_path = os.path.join(_CACHE_DIR, f"{name}_{tag}.so")
    if os.path.exists(so_path):
        return so_path
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-o", so_path,
           *extra_cflags, *sources]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"cpp_extension build failed:\n{proc.stderr}")
    return so_path


class _HostOp:
    """One C symbol wrapped as a framework op via pure_callback."""

    def __init__(self, lib, name: str, grad_name: Optional[str]):
        self._fn = getattr(lib, name)
        self._fn.argtypes = [ctypes.POINTER(ctypes.c_float),
                             ctypes.POINTER(ctypes.c_float), ctypes.c_int64]
        self._fn.restype = None
        self.name = name
        self._grad = None
        if grad_name is not None:
            g = getattr(lib, grad_name)
            g.argtypes = [ctypes.POINTER(ctypes.c_float),
                          ctypes.POINTER(ctypes.c_float),
                          ctypes.POINTER(ctypes.c_float), ctypes.c_int64]
            g.restype = None
            self._grad = g

    def _host_fwd(self, x: np.ndarray) -> np.ndarray:
        x = np.ascontiguousarray(x, np.float32)
        y = np.empty_like(x)
        self._fn(x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                 y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), x.size)
        return y

    def _host_bwd(self, x: np.ndarray, gy: np.ndarray) -> np.ndarray:
        x = np.ascontiguousarray(x, np.float32)
        gy = np.ascontiguousarray(gy, np.float32)
        gx = np.empty_like(x)
        self._grad(x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                   gy.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                   gx.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), x.size)
        return gx

    def kernel(self):
        """Build (once) and cache the jax-facing callable — a stable identity
        so jit tracing caches hold across calls."""
        cached = getattr(self, "_kernel", None)
        if cached is not None:
            return cached
        host_fwd, host_bwd = self._host_fwd, self._host_bwd

        def fwd_cb(a):
            return jax.pure_callback(
                host_fwd, jax.ShapeDtypeStruct(a.shape, jnp.float32),
                a.astype(jnp.float32), vmap_method="sequential")

        if self._grad is None:
            self._kernel = fwd_cb
            return fwd_cb

        f = jax.custom_vjp(fwd_cb)

        def fwd(a):
            return fwd_cb(a), a

        def bwd(a, gy):
            gx = jax.pure_callback(
                host_bwd, jax.ShapeDtypeStruct(a.shape, jnp.float32),
                a, gy.astype(jnp.float32), vmap_method="sequential")
            return (gx,)

        f.defvjp(fwd, bwd)
        self._kernel = f
        return f

    def __call__(self, x):
        return apply_fn(f"custom_cpp_{self.name}", self.kernel(), x)


class CppExtensionModule:
    def __init__(self, so_path: str):
        self._lib = ctypes.CDLL(so_path)
        self.so_path = so_path
        self._lib.pt_op_list.restype = ctypes.c_char_p
        names = self._lib.pt_op_list().decode().split(",")
        self.op_names: List[str] = [n.strip() for n in names if n.strip()]
        for n in self.op_names:
            grad_name = f"{n}_grad" if hasattr(self._lib, f"{n}_grad") else None
            # _HostOp.__call__ dispatches through apply_fn with its cached kernel
            setattr(self, n, _HostOp(self._lib, n, grad_name))


def load(name: str, sources: Sequence[str], extra_cflags=(),
         verbose: bool = False) -> CppExtensionModule:
    """JIT-compile C++ sources and expose their ops
    (reference: cpp_extension.load)."""
    so = _build(name, sources, tuple(extra_cflags))
    return CppExtensionModule(so)
