"""paddle_tpu.incubate (reference: python/paddle/incubate)."""

from . import asp  # noqa: F401
from . import distributed, nn  # noqa: F401
