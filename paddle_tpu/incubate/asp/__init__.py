"""paddle_tpu.incubate.asp — Automatic SParsity (2:4 structured sparsity).

Parity anchors: the reference's ASP package
(python/paddle/incubate/asp/__init__.py — calculate_density, decorate,
prune_model, set/reset_excluded_layers, add_supported_layer; utils.py:192
get_mask_1d, :334 get_mask_2d_greedy, get_mask_2d_best, check_mask_1d/2d;
asp.py:233 decorate → OptimizerWithSparsityGuarantee, :319 prune_model).

TPU note: the reference targets NVIDIA sparse tensor cores; the MXU has no
2:4 hardware path, so here ASP is a MODEL-COMPRESSION workflow: masks are
computed host-side (numpy, like the reference's utils), applied to weights,
and re-applied after each optimizer step so training keeps the n:m pattern
(the reference's OptimizerWithSparsityGuarantee contract).
"""

from __future__ import annotations

import itertools
from enum import Enum
import numpy as np

from ...core.tensor import Tensor

__all__ = [
    "calculate_density", "decorate", "prune_model",
    "set_excluded_layers", "reset_excluded_layers", "add_supported_layer",
    "MaskAlgo", "CheckMethod", "get_mask_1d", "get_mask_2d_greedy",
    "get_mask_2d_best", "check_mask_1d", "check_mask_2d", "create_mask",
    "check_sparsity",
]


class MaskAlgo(Enum):
    MASK_1D = "get_mask_1d"
    MASK_2D_GREEDY = "get_mask_2d_greedy"
    MASK_2D_BEST = "get_mask_2d_best"


class CheckMethod(Enum):
    CHECK_1D = "check_mask_1d"
    CHECK_2D = "check_mask_2d"

    @staticmethod
    def get_checking_method(mask_algo: "MaskAlgo") -> "CheckMethod":
        """CHECK_1D for MASK_1D, CHECK_2D for the 2D algos (utils.py:57)."""
        return (CheckMethod.CHECK_1D if mask_algo == MaskAlgo.MASK_1D
                else CheckMethod.CHECK_2D)


def calculate_density(x) -> float:
    """nonzero fraction of x (utils.py:86)."""
    a = np.asarray(x._data if isinstance(x, Tensor) else x)
    return float(np.count_nonzero(a)) / max(a.size, 1)


def _pad_cols(mat, m):
    pad = (-mat.shape[1]) % m
    if pad:
        mat = np.concatenate([mat, np.zeros((mat.shape[0], pad), mat.dtype)],
                             axis=1)
    return mat, pad


def check_mask_1d(mat, n: int, m: int) -> bool:
    """True iff every 1 x m block has >= n zeros (utils.py:142)."""
    mat = np.asarray(mat)
    mat, _ = _pad_cols(mat.reshape(mat.shape[0], -1) if mat.ndim > 1
                       else mat.reshape(1, -1), m)
    blocks = mat.reshape(-1, m)
    return bool(((blocks == 0).sum(axis=1) >= n).all())


def get_mask_1d(mat, n: int, m: int):
    """Keep the m-n largest |values| per 1 x m block — at least n zeros per
    block (utils.py:192). 1-D input is treated as one row, matching
    check_mask_1d."""
    mat = np.asarray(mat)
    if mat.ndim == 1:
        return get_mask_1d(mat.reshape(1, -1), n, m).reshape(-1)
    orig_cols = mat.shape[1]
    padded, pad = _pad_cols(mat, m)
    blocks = np.abs(padded.reshape(-1, m))
    keep = m - n
    # argsort ascending; zero out the n smallest per block
    order = np.argsort(blocks, axis=1, kind="stable")
    mask = np.zeros_like(blocks, dtype=mat.dtype)
    np.put_along_axis(mask, order[:, -keep:] if keep else order[:, :0],
                      1, axis=1)
    mask = mask.reshape(padded.shape)[:, :orig_cols]
    return mask


def check_mask_2d(mat, n: int, m: int) -> bool:
    """True iff every m x m block has >= n zeros per row AND per column
    (utils.py:277)."""
    mat = np.asarray(mat)
    r_pad = (-mat.shape[0]) % m
    c_pad = (-mat.shape[1]) % m
    mat = np.pad(mat, ((0, r_pad), (0, c_pad)))
    R, C = mat.shape
    for i in range(0, R, m):
        for j in range(0, C, m):
            b = mat[i:i + m, j:j + m]
            if ((b == 0).sum(axis=1) < n).any() or \
                    ((b == 0).sum(axis=0) < n).any():
                return False
    return True


def get_mask_2d_greedy(mat, n: int, m: int):
    """Per m x m block, keep entries in descending |value| while each row
    and column keeps at most m-n (utils.py:334)."""
    mat = np.asarray(mat)
    orig = mat.shape
    r_pad = (-mat.shape[0]) % m
    c_pad = (-mat.shape[1]) % m
    p = np.pad(mat, ((0, r_pad), (0, c_pad)))
    mask = np.zeros_like(p, dtype=mat.dtype)
    keep = m - n
    R, C = p.shape
    for i in range(0, R, m):
        for j in range(0, C, m):
            b = np.abs(p[i:i + m, j:j + m])
            rk = np.zeros(m, np.int32)
            ck = np.zeros(m, np.int32)
            for flat in np.argsort(-b, axis=None, kind="stable"):
                r, c = divmod(int(flat), m)
                if rk[r] < keep and ck[c] < keep:
                    mask[i + r, j + c] = 1
                    rk[r] += 1
                    ck[c] += 1
    return mask[:orig[0], :orig[1]]


_2D_PATTERNS: dict = {}


def _valid_2d_patterns(n, m):
    """All m x m 0/1 patterns with exactly m-n kept per row and column
    (reference _compute_valid_2d_patterns)."""
    key = (n, m)
    if key not in _2D_PATTERNS:
        keep = m - n
        rows = [np.asarray(p) for p in itertools.combinations(range(m), keep)]
        row_masks = []
        for p in rows:
            r = np.zeros(m, np.int64)
            r[list(p)] = 1
            row_masks.append(r)
        pats = []
        for combo in itertools.product(row_masks, repeat=m):
            g = np.stack(combo)
            if (g.sum(axis=0) == keep).all():
                pats.append(g)
        _2D_PATTERNS[key] = np.stack(pats)
    return _2D_PATTERNS[key]


def get_mask_2d_best(mat, n: int, m: int):
    """Exhaustive per-block search over all valid 2D n:m patterns, keeping
    the one with maximal |value| sum (utils.py get_mask_2d_best)."""
    mat = np.asarray(mat)
    orig = mat.shape
    r_pad = (-mat.shape[0]) % m
    c_pad = (-mat.shape[1]) % m
    p = np.pad(mat, ((0, r_pad), (0, c_pad)))
    pats = _valid_2d_patterns(n, m)  # [P, m, m]
    mask = np.zeros_like(p, dtype=mat.dtype)
    R, C = p.shape
    for i in range(0, R, m):
        for j in range(0, C, m):
            b = np.abs(p[i:i + m, j:j + m])
            scores = (pats * b[None]).sum(axis=(1, 2))
            mask[i:i + m, j:j + m] = pats[int(np.argmax(scores))]
    return mask[:orig[0], :orig[1]]


def _algo_value(name: str) -> str:
    """Normalize 'mask_1d' / '1d' / 'get_mask_1d' to the enum value."""
    if name.startswith("get_mask_"):
        return name
    if name.startswith("mask_"):
        return "get_" + name
    return "get_mask_" + name


def _as_2d(t):
    """Weight layout handling like the reference's create_mask: 1-D as one
    row, 2-D as-is, 3/4-D flattened to [dim0, rest]."""
    a = np.asarray(t)
    if a.ndim == 1:
        return a.reshape(1, -1), a.shape
    if a.ndim == 2:
        return a, a.shape
    return a.reshape(a.shape[0], -1), a.shape


def create_mask(tensor, func_name=MaskAlgo.MASK_1D, n: int = 2, m: int = 4):
    """n:m mask for a weight tensor of any rank (utils.py create_mask)."""
    if isinstance(func_name, str):
        func_name = MaskAlgo(_algo_value(func_name))
    mat, shape = _as_2d(tensor._data if isinstance(tensor, Tensor) else tensor)
    fn = globals()[func_name.value]
    return fn(mat, n, m).reshape(shape)


def check_sparsity(tensor, func_name=CheckMethod.CHECK_1D, n: int = 2,
                   m: int = 4) -> bool:
    """Check a weight tensor of any rank against the n:m pattern."""
    if isinstance(func_name, str):
        func_name = CheckMethod(func_name if func_name.startswith("check_")
                                else f"check_mask_{func_name}")
    mat, _ = _as_2d(tensor._data if isinstance(tensor, Tensor) else tensor)
    return globals()[func_name.value](mat, n, m)


# ---------------------------------------------------------------------------
# workflow: excluded layers, prune_model, decorate
# ---------------------------------------------------------------------------

_EXCLUDED: set = set()
_SUPPORTED_TYPES = {"Linear", "Conv2D"}


def set_excluded_layers(layers, main_program=None):
    """Exclude layers (by full_name/parameter name prefix) from pruning
    (asp.py:55)."""
    for name in layers:
        _EXCLUDED.add(str(name))


def reset_excluded_layers(main_program=None):
    """Clear the exclusion list (asp.py:144)."""
    _EXCLUDED.clear()


def add_supported_layer(layer, pruning_func=None):
    """Register an additional layer TYPE as prunable (supported_layer_list.py:96)."""
    name = layer if isinstance(layer, str) else type(layer).__name__ \
        if not isinstance(layer, type) else layer.__name__
    _SUPPORTED_TYPES.add(name)


def _prunable_params(model):
    for lname, layer in model.named_sublayers():
        if type(layer).__name__ not in _SUPPORTED_TYPES:
            continue
        if any(lname == e or lname.startswith(e + ".") for e in _EXCLUDED):
            continue
        w = getattr(layer, "weight", None)
        if w is not None and w._data is not None and w._data.ndim >= 2:
            yield lname, w


def prune_model(model, n: int = 2, m: int = 4, mask_algo: str = "mask_1d",
                with_mask: bool = True):
    """Prune every supported layer's weight to the n:m pattern and (with
    ``with_mask``) remember the masks so :func:`decorate`-wrapped optimizers
    keep the pattern through training (asp.py:319).

    2-D weights are masked on the TRANSPOSED matrix like the reference's
    _default_pruning (ASP's hardware pattern is along the reduction dim).
    Returns {param_name: mask ndarray}.
    """
    import jax.numpy as jnp

    algo = MaskAlgo(_algo_value(mask_algo))
    masks = {}
    for lname, w in _prunable_params(model):
        a = np.asarray(w._data)
        if a.ndim == 2:
            mask = create_mask(a.T, algo, n, m).T
        else:
            mask = create_mask(a, algo, n, m)
        w._data = jnp.asarray(a * mask)
        if with_mask:
            # mask rides the param Tensor itself: lifetime is the model's,
            # and decorate() discovers exactly its optimizer's params
            w._asp_mask = mask
        masks[lname + ".weight"] = mask
    return masks


class OptimizerWithSparsityGuarantee:
    """Wraps an optimizer so every step() re-applies the pruning masks —
    the reference's decorate() contract (asp.py:233): masked weights stay
    masked through training. Masks are the ``_asp_mask`` attributes
    prune_model left on THIS optimizer's params (no process-global state)."""

    def __init__(self, optimizer):
        self._optimizer = optimizer

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def step(self):
        import jax.numpy as jnp

        self._optimizer.step()
        for w in self._optimizer._parameter_list or []:
            mask = getattr(w, "_asp_mask", None)
            if mask is not None and w._data is not None:
                w._data = w._data * jnp.asarray(mask, w._data.dtype)


def decorate(optimizer) -> OptimizerWithSparsityGuarantee:
    """Wrap ``optimizer`` to maintain ASP masks after each step (asp.py:233)."""
    return OptimizerWithSparsityGuarantee(optimizer)
