"""incubate.nn.functional — fused ops (reference: python/paddle/incubate/nn/functional).

TPU-native: most of the reference's 75 fused CUDA kernels
(phi/kernels/fusion/gpu) are XLA fusions here — the functions below express the
fused computation as one traced region; XLA emits a single TPU kernel chain.
Attention variants route to the Pallas flash kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ....core.op_registry import apply_fn
from ....core.tensor import Tensor
from ....nn import functional as F


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6, begin_norm_axis=-1,
                   bias=None, residual=None, quant_scale=-1, **kw):
    """Reference: incubate/nn/functional/fused_rms_norm.py."""

    def fn(a, w, *rest):
        i = 0
        res = None
        if residual is not None:
            res = rest[i]
            i += 1
        if res is not None:
            a = a + res
        dt = a.dtype
        af = a.astype(jnp.float32)
        ms = jnp.mean(jnp.square(af), axis=-1, keepdims=True)
        out = (af * jax.lax.rsqrt(ms + epsilon)).astype(dt) * w
        if norm_bias is not None:
            out = out + rest[i]
        return out

    args = [x, norm_weight] + [t for t in (residual, norm_bias) if t is not None]
    return apply_fn("fused_rms_norm", fn, *args)


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5, begin_norm_axis=-1,
                     bias=None, residual=None, **kw):
    if residual is not None:
        x = x + residual
    return F.layer_norm(x, x.shape[begin_norm_axis:] if begin_norm_axis != -1 else [x.shape[-1]],
                        norm_weight, norm_bias, epsilon)


def swiglu(x, y=None, name=None):
    """Reference: incubate/nn/functional/swiglu.py — silu(x) * y (fused gate)."""
    if y is None:
        def fn(a):
            a1, a2 = jnp.split(a, 2, axis=-1)
            return jax.nn.silu(a1) * a2

        return apply_fn("swiglu", fn, x)
    return apply_fn("swiglu", lambda a, b: jax.nn.silu(a) * b, x, y)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0):
    """Reference: incubate/nn/functional/fused_rotary_position_embedding.py.
    q/k/v layout: [batch, seq, heads, head_dim]."""

    def rope_one(t, s, c):
        # shared rotation primitive — same code path as the serving ops' in-op
        # rope (_rope_one below), so the two conventions cannot drift
        return _rope_one(t, c, s, use_neox_rotary_style)

    def make_sincos(seq_len, dim, dtype):
        inv = 1.0 / (rotary_emb_base ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
        tpos = jnp.arange(seq_len, dtype=jnp.float32)
        freqs = jnp.outer(tpos, inv)
        if use_neox_rotary_style:
            emb = jnp.concatenate([freqs, freqs], axis=-1)
        else:
            emb = jnp.repeat(freqs, 2, axis=-1)
        return jnp.sin(emb).astype(dtype)[None, :, None, :], jnp.cos(emb).astype(dtype)[None, :, None, :]

    outs = []
    tensors = [t for t in (q, k, v) if t is not None]

    def fn(*arrs):
        rest = list(arrs)
        n_t = len(tensors)
        main = rest[:n_t]
        extra = rest[n_t:]
        if sin is not None:
            s, c = extra[0], extra[1]
            if s.ndim == 2:
                s = s[None, :, None, :]
                c = c[None, :, None, :]
            elif s.ndim == 4 and s.shape[2] != 1 and s.shape[1] != main[0].shape[1]:
                pass
        else:
            s, c = make_sincos(main[0].shape[1], main[0].shape[-1], main[0].dtype)
        if position_ids is not None:
            pid = extra[-1]
            s = jnp.take(s[0, :, 0, :], pid, axis=0)[:, :, None, :]
            c = jnp.take(c[0, :, 0, :], pid, axis=0)[:, :, None, :]
        return tuple(rope_one(t, s, c) for t in main)

    args = tensors + [t for t in (sin, cos) if t is not None]
    if position_ids is not None:
        args = args + [position_ids]
    res = apply_fn("fused_rope", fn, *args)
    res = list(res) if isinstance(res, tuple) else [res]
    out = []
    i = 0
    for t in (q, k, v):
        if t is not None:
            out.append(res[i])
            i += 1
        else:
            out.append(None)
    return tuple(out)


def fused_bias_act(x, bias=None, act_method="gelu", **kw):
    if bias is not None:
        x = x + bias
    return getattr(F, act_method)(x)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    def fn(a, w, *b):
        ww = w.T if transpose_weight else w
        out = jnp.matmul(a, ww)
        if b:
            out = out + b[0]
        return out

    args = [x, weight] + ([bias] if bias is not None else [])
    return apply_fn("fused_linear", fn, *args)


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False, activation="gelu"):
    def fn(a, w, b):
        if trans_x:
            a = a.T
        if trans_y:
            w = w.T
        return getattr(jax.nn, activation if activation != "none" else "identity",
                       lambda v: v)(jnp.matmul(a, w) + b)

    return apply_fn("fused_linear_activation", fn, x, y, bias)


def fused_moe(x, gate_weight, ffn1_weight, ffn2_weight, ffn1_bias=None,
              ffn1_scale=None, ffn2_bias=None, ffn2_scale=None,
              quant_method="None", moe_topk=2, norm_topk_prob=True,
              capacity_factor=None):
    """Reference: incubate/nn/functional/fused_moe.py:22 — same signature.

    x: [bsz, seq, d_model]; gate_weight: per-token gate logits
    [bsz, seq, num_experts]; ffn1_weight: [E, d_model, d_ff*2] (gated — swiglu
    split) or [E, d_model, d_ff] (plain gelu); ffn2_weight: [E, d_ff, d_model];
    biases [E, 1, d] or [E, d]. quant_method/scales unsupported (as in the
    reference's CPU path).

    One traced region: topk dispatch + batched expert FFN + combine (shared
    routing in incubate.distributed.models.moe). Deviation from the CUDA
    kernel: a dense-dispatch capacity bounds expert buffers; the default
    ``capacity_factor=None`` sets capacity = num tokens (NO token drops, exact
    reference semantics) — pass e.g. 1.25 to bound memory on long sequences.
    """
    import math

    if quant_method != "None" or ffn1_scale is not None or ffn2_scale is not None:
        raise NotImplementedError("fused_moe quantization is not supported")

    from ...distributed.models.moe.moe_layer import routed_ffn

    def fn(x, gate_logits, w1, w2, b1, b2):
        orig = x.shape
        d_model = orig[-1]
        tokens = x.reshape(-1, d_model)
        n, e = tokens.shape[0], w1.shape[0]
        probs = jax.nn.softmax(
            gate_logits.reshape(-1, e).astype(jnp.float32), axis=-1)
        if capacity_factor is None:
            cap = n
        else:
            cap = max(int(math.ceil(n * moe_topk * capacity_factor / e)), moe_topk)
        gated = w1.shape[-1] == 2 * w2.shape[-2]

        def expert_fn(expert_in):
            h = jnp.einsum("ecd,edm->ecm", expert_in, w1)
            if b1 is not None:
                h = h + b1.reshape(e, 1, -1)
            if gated:
                half = h.shape[-1] // 2
                h = jax.nn.silu(h[..., :half]) * h[..., half:]
            else:
                h = jax.nn.gelu(h)
            out = jnp.einsum("ecm,emd->ecd", h, w2)
            if b2 is not None:
                out = out + b2.reshape(e, 1, -1)
            return out

        y, _ = routed_ffn(tokens, probs, expert_fn, moe_topk, cap,
                          renormalize=norm_topk_prob)
        return y.astype(x.dtype).reshape(orig)

    return apply_fn("fused_moe", fn, x, gate_weight, ffn1_weight, ffn2_weight,
                    ffn1_bias, ffn2_bias)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train", name=None):
    return F.dropout(x, p, training=training, mode=mode) + y


def fused_multi_head_attention(x, qkv_weight, linear_weight, pre_layer_norm=False,
                               pre_ln_scale=None, pre_ln_bias=None, ln_scale=None,
                               ln_bias=None, pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None, attn_mask=None,
                               dropout_rate=0.5, attn_dropout_rate=0.5,
                               ln_epsilon=1e-5, training=True,
                               mode="upscale_in_train", ring_id=-1,
                               add_residual=True, num_heads=-1,
                               transpose_qkv_wb=False, name=None):
    """Fused self-attention block (reference:
    incubate/nn/functional/fused_transformer.py:513 — pseudo code at :546).

    x: [b, s, dim]. qkv_weight: [3, nh, hd, dim] (or [dim, 3*dim] when
    ``transpose_qkv_wb``, which requires ``num_heads``). cache_kv
    [2, b, nh, cache_len, hd] appends this call's K/V (generation); the
    updated cache is written back into the ``cache_kv`` tensor (reference
    in-place contract) and attention spans cache + current."""
    # ONE (name, tensor) list drives both the positional args and the in-fn
    # binding — they cannot drift. Missing LN biases become zeros.
    dim0 = x.shape[-1]
    zeros = lambda: jnp.zeros((dim0,), jnp.float32)
    opt = []
    if pre_layer_norm and pre_ln_scale is not None:
        opt += [("pls", pre_ln_scale),
                ("plb", pre_ln_bias if pre_ln_bias is not None else zeros())]
    if qkv_bias is not None:
        opt += [("qb", qkv_bias)]
    if linear_bias is not None:
        opt += [("lb", linear_bias)]
    if cache_kv is not None:
        opt += [("cache", cache_kv)]
    if attn_mask is not None:
        opt += [("mask", attn_mask)]
    if not pre_layer_norm and ln_scale is not None:
        opt += [("lns", ln_scale),
                ("lnb", ln_bias if ln_bias is not None else zeros())]
    opt_names = [n for n, _ in opt]

    def fn(xx, qkvw, lw, *rest):
        r = dict(zip(opt_names, rest))

        b, s, dim = xx.shape
        residual = xx
        h = xx
        if pre_layer_norm:
            mean = jnp.mean(h, -1, keepdims=True)
            var = jnp.var(h, -1, keepdims=True)
            h = (h - mean) * jax.lax.rsqrt(var + pre_ln_epsilon)
            if "pls" in r:
                h = h * r["pls"] + r["plb"]
        if transpose_qkv_wb:
            if num_heads is None or num_heads <= 0:
                raise ValueError(
                    "fused_multi_head_attention(transpose_qkv_wb=True) "
                    "requires num_heads (the 2-D qkv weight cannot infer it)")
            nh = num_heads
            hd = dim // nh
            qkv = jnp.matmul(h, qkvw)                     # [b, s, 3*dim]
            if "qb" in r:
                qkv = qkv + r["qb"]
            qkv = qkv.reshape(b, s, 3, nh, hd)
        else:
            _, nh, hd, _ = qkvw.shape
            qkv = jnp.einsum("bsd,tnhd->bstnh", h, qkvw)  # [b, s, 3, nh, hd]
            if "qb" in r:
                qkv = qkv + r["qb"][None, None]
        q = jnp.swapaxes(qkv[:, :, 0], 1, 2)              # [b, nh, s, hd]
        k = jnp.swapaxes(qkv[:, :, 1], 1, 2)
        v = jnp.swapaxes(qkv[:, :, 2], 1, 2)
        new_cache = None
        if "cache" in r:
            k = jnp.concatenate([r["cache"][0], k], axis=2)
            v = jnp.concatenate([r["cache"][1], v], axis=2)
            new_cache = jnp.stack([k, v])
        logits = jnp.einsum("bnqh,bnkh->bnqk", q, k).astype(jnp.float32) * (hd ** -0.5)
        if "mask" in r:
            m = r["mask"]
            if m.dtype == jnp.bool_:
                m = jnp.where(m, 0.0, -1e9)
            elif jnp.issubdtype(m.dtype, jnp.integer):
                m = jnp.where(m != 0, 0.0, -1e9)
            logits = logits + m.astype(jnp.float32)
        probs = jax.nn.softmax(logits, -1).astype(xx.dtype)
        if attn_dropout_rate and training:
            from ....framework.random import next_key

            keep = jax.random.bernoulli(next_key(), 1.0 - attn_dropout_rate, probs.shape)
            probs = jnp.where(keep, probs / (1.0 - attn_dropout_rate), 0.0)
        ctx = jnp.einsum("bnqk,bnkh->bnqh", probs, v)
        ctx = jnp.swapaxes(ctx, 1, 2).reshape(b, s, dim)
        out = jnp.matmul(ctx, lw)
        if "lb" in r:
            out = out + r["lb"]
        if dropout_rate and training:
            from ....framework.random import next_key

            keep = jax.random.bernoulli(next_key(), 1.0 - dropout_rate, out.shape)
            out = jnp.where(keep, out / (1.0 - dropout_rate), 0.0)
        if add_residual:
            out = residual + out
        if not pre_layer_norm:
            mean = jnp.mean(out, -1, keepdims=True)
            var = jnp.var(out, -1, keepdims=True)
            out = (out - mean) * jax.lax.rsqrt(var + ln_epsilon)
            if "lns" in r:
                out = out * r["lns"] + r["lnb"]
        if new_cache is not None:
            return out, new_cache
        return out

    args = [x, qkv_weight, linear_weight] + [t for _, t in opt]
    res = apply_fn("fused_multi_head_attention", fn, *args)
    if cache_kv is not None:
        out, new_cache = res
        cache_kv._data = new_cache._data  # reference in-place cache contract
        return out, new_cache  # reference returns (final_out, cache_kv_out)
    return res


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu", ln1_epsilon=1e-5,
                      ln2_epsilon=1e-5, pre_layer_norm=False, training=True,
                      mode="upscale_in_train", ring_id=-1, add_residual=True,
                      name=None):
    """Fused transformer FFN block (reference:
    incubate/nn/functional/fused_transformer.py:47): [LN ->] linear1 -> act ->
    dropout1 -> linear2 -> dropout2 -> +residual [-> LN] in one traced region."""

    dim0 = x.shape[-1]
    zeros = lambda: jnp.zeros((dim0,), jnp.float32)
    opt = []
    if linear1_bias is not None:
        opt += [("b1", linear1_bias)]
    if linear2_bias is not None:
        opt += [("b2", linear2_bias)]
    if ln1_scale is not None:
        opt += [("s1", ln1_scale),
                ("bb1", ln1_bias if ln1_bias is not None else zeros())]
    if ln2_scale is not None:
        opt += [("s2", ln2_scale),
                ("bb2", ln2_bias if ln2_bias is not None else zeros())]
    opt_names = [n for n, _ in opt]

    def fn(xx, w1, w2, *rest):
        r = dict(zip(opt_names, rest))

        def ln(t, scale, bias, eps):
            mean = jnp.mean(t, -1, keepdims=True)
            var = jnp.var(t, -1, keepdims=True)
            out = (t - mean) * jax.lax.rsqrt(var + eps)
            if scale is not None:
                out = out * scale + bias
            return out

        def drop(t, rate):
            if rate and training:
                from ....framework.random import next_key

                keep = jax.random.bernoulli(next_key(), 1.0 - rate, t.shape)
                return jnp.where(keep, t / (1.0 - rate), 0.0)
            return t

        residual = xx
        h = xx
        if pre_layer_norm:
            h = ln(h, r.get("s1"), r.get("bb1"), ln1_epsilon)
        h = jnp.matmul(h, w1)
        if "b1" in r:
            h = h + r["b1"]
        act = getattr(jax.nn, activation, None)
        if act is None:
            raise ValueError(f"fused_feedforward: unknown activation "
                             f"'{activation}' (not a jax.nn function)")
        h = act(h)
        h = drop(h, dropout1_rate)
        h = jnp.matmul(h, w2)
        if "b2" in r:
            h = h + r["b2"]
        h = drop(h, dropout2_rate)
        if add_residual:
            h = residual + h
        if not pre_layer_norm:  # post-LN architecture normalizes with ln2
            h = ln(h, r.get("s2"), r.get("bb2"), ln2_epsilon)
        return h

    args = [x, linear1_weight, linear2_weight] + [t for _, t in opt]
    return apply_fn("fused_feedforward", fn, *args)


def _rope_one(t, cos, sin, neox):
    """THE rotation primitive (used by fused_rotary_position_embedding AND the
    serving ops' in-op rope) given FULL-head-dim cos/sin tables.

    neox=True: half-rotation (GPT-NeoX); neox=False: interleaved
    rotate-every-two (GPT-J) — matching the reference kernel's two styles
    (masked_multihead_attention_kernel.cu:247 neox branch)."""
    if neox:
        h = t.shape[-1] // 2
        rot = jnp.concatenate([-t[..., h:], t[..., :h]], -1)
    else:
        rot = jnp.stack([-t[..., 1::2], t[..., 0::2]], -1).reshape(t.shape)
    return t * cos + rot * sin


def _rope_pair(q, k, cos, sin, neox):
    return _rope_one(q, cos, sin, neox), _rope_one(k, cos, sin, neox)


def _expand_rope_tables(cos_h, sin_h, hd, neox):
    """Half-size ([..., hd//2]) reference tables -> full head_dim, per style."""
    if cos_h.shape[-1] == hd:
        return cos_h, sin_h
    if neox:
        return (jnp.concatenate([cos_h, cos_h], -1),
                jnp.concatenate([sin_h, sin_h], -1))
    return jnp.repeat(cos_h, 2, -1), jnp.repeat(sin_h, 2, -1)


def _quant_cache(x, scales, round_type, qmax, qmin):
    """Per-kv-head static cache quantization (reference cache_k_quant_scales
    semantics): int8 = clip(round(x * scale[head]), qmin, qmax)."""
    s = x.astype(jnp.float32) * scales.reshape(1, -1, 1)
    if round_type == 0:
        r = jnp.round(s)                      # round-half-to-even
    else:
        r = jnp.sign(s) * jnp.floor(jnp.abs(s) + 0.5)  # half away from zero
    return jnp.clip(r, qmin, qmax).astype(jnp.int8)


def masked_multihead_attention(x, cache_kv=None, bias=None, src_mask=None,
                               cum_offsets=None, sequence_lengths=None,
                               rotary_tensor=None, beam_cache_offset=None,
                               qkv_out_scale=None, out_shift=None, out_smooth=None,
                               seq_len=1, rotary_emb_dims=0,
                               use_neox_rotary_style=False,
                               compute_dtype="default", out_scale=-1,
                               quant_round_type=1, quant_max_bound=127.0,
                               quant_min_bound=-127.0):
    """Decode-time fused MHA over a dense KV cache (reference:
    incubate/nn/functional/masked_multihead_attention.py:74 over
    masked_multihead_attention_kernel.cu).

    x: [b, 3*nh*hd] — ONE new token per sequence. cache_kv:
    [2, b, nh, max_seq_len, hd]. ``sequence_lengths`` [b] or [b, 1] gives each
    row's current cache length (write position); attention spans positions
    0..len inclusive. Returns (out [b, nh*hd], cache_kv) — the cache tensor is
    also updated in place like the reference."""
    if qkv_out_scale is not None or out_scale != -1:
        raise NotImplementedError("masked_multihead_attention quantization")
    if rotary_emb_dims not in (0, 1):
        raise NotImplementedError(
            "masked_multihead_attention rotary_emb_dims=2 (pos_ids_extra "
            "2-section rope) is not supported")
    if rotary_emb_dims and rotary_tensor is None:
        raise ValueError("rotary_emb_dims=1 requires rotary_tensor")
    if sequence_lengths is None:
        raise ValueError(
            "masked_multihead_attention requires sequence_lengths (each row's "
            "current cache length / write position)")
    if rotary_emb_dims and rotary_tensor is not None and cache_kv is not None:
        import numpy as _np

        from ....core.tensor import unwrap as _unwrap

        # shape-only coverage check (no host sync, trace-safe): positions are
        # bounded by the cache's max_seq, so a table with a seq axis must
        # span it — otherwise indexing would silently clamp to the last row
        rshape = _unwrap(rotary_tensor).shape
        seq_axis = int(_np.prod(rshape[2:-1]))
        max_seq_c = _unwrap(cache_kv).shape[3]
        if seq_axis > 1 and seq_axis < max_seq_c:
            raise ValueError(
                f"rotary_tensor covers {seq_axis} positions but the cache "
                f"holds up to {max_seq_c} — decode positions past the table "
                "would silently clamp to the last row's rotation")

    opt = []
    if bias is not None:
        opt += [("bias", bias)]
    if src_mask is not None:
        opt += [("mask", src_mask)]
    if rotary_emb_dims:
        opt += [("rope", rotary_tensor)]
    opt_names = [n for n, _ in opt]

    def fn(xx, cache, lens, *rest):
        r = dict(zip(opt_names, rest))
        _, b, nh, max_seq, hd = cache.shape
        qkv = xx.reshape(b, 3, nh, hd)
        if "bias" in r:
            qkv = qkv + r["bias"].reshape(1, 3, nh, hd)
        q, k_new, v_new = qkv[:, 0], qkv[:, 1], qkv[:, 2]   # [b, nh, hd]
        pos = lens.reshape(b).astype(jnp.int32)
        if "rope" in r:
            # reference layout [2, B, rotary_seq_len, 1, Dh]
            # (masked_multihead_attention_kernel.cu:46): cos then sin; a
            # seq axis > 1 is indexed at each row's current position
            rot = r["rope"]
            if rot.shape[0] != 2 or rot.shape[1] not in (1, b):
                raise ValueError(
                    "rotary_tensor must be [2, batch (or 1), seq, 1, "
                    f"head_dim] (cos;sin), got shape {rot.shape}")
            if rot.shape[1] == 1 and b > 1:   # batch-broadcast table
                rot = jnp.broadcast_to(rot, (2, b) + rot.shape[2:])
            rot = rot.reshape(2, b, -1, rot.shape[-1]).astype(jnp.float32)
            if rot.shape[2] > 1:
                bidx0 = jnp.arange(b)
                cos_t, sin_t = rot[0][bidx0, pos], rot[1][bidx0, pos]
            else:
                cos_t, sin_t = rot[0][:, 0], rot[1][:, 0]
            cos_t, sin_t = _expand_rope_tables(cos_t, sin_t, hd,
                                               use_neox_rotary_style)
            qf, kf = _rope_pair(q.astype(jnp.float32),
                                k_new.astype(jnp.float32),
                                cos_t[:, None, :], sin_t[:, None, :],
                                use_neox_rotary_style)
            q, k_new = qf.astype(q.dtype), kf.astype(k_new.dtype)
        bidx = jnp.arange(b)
        kc = cache[0].at[bidx, :, pos, :].set(k_new.astype(cache.dtype))
        vc = cache[1].at[bidx, :, pos, :].set(v_new.astype(cache.dtype))
        logits = jnp.einsum("bnh,bnsh->bns", q.astype(jnp.float32),
                            kc.astype(jnp.float32)) * (hd ** -0.5)
        valid = jnp.arange(max_seq)[None, None, :] <= pos[:, None, None]
        logits = jnp.where(valid, logits, -1e30)
        if "mask" in r:
            m = r["mask"].reshape(b, 1, -1).astype(jnp.float32)
            logits = logits + jnp.pad(m, ((0, 0), (0, 0), (0, max_seq - m.shape[-1])))
        probs = jax.nn.softmax(logits, -1)
        out = jnp.einsum("bns,bnsh->bnh", probs, vc.astype(jnp.float32))
        return out.reshape(b, nh * hd).astype(xx.dtype), jnp.stack([kc, vc])

    args = [x, cache_kv, sequence_lengths] + [t for _, t in opt]
    out, new_cache = apply_fn("masked_multihead_attention", fn, *args)
    cache_kv._data = new_cache._data  # reference in-place cache contract
    if beam_cache_offset is not None:
        return out, new_cache, beam_cache_offset
    return out, new_cache


def variable_length_memory_efficient_attention(q, k, v, seq_lens=None, kv_seq_lens=None, mask=None, scale=None, causal=False):
    return F.scaled_dot_product_attention(q, k, v, attn_mask=mask, is_causal=causal)


def block_multihead_attention(qkv, key_cache, value_cache, seq_lens_encoder,
                              seq_lens_decoder, seq_lens_this_time,
                              padding_offsets, cum_offsets, cu_seqlens_q,
                              cu_seqlens_k, block_tables, pre_key_cache=None,
                              pre_value_cache=None, cache_k_quant_scales=None,
                              cache_v_quant_scales=None,
                              cache_k_dequant_scales=None,
                              cache_v_dequant_scales=None, qkv_out_scale=None,
                              qkv_bias=None, out_shift=None, out_smooth=None,
                              max_enc_len_this_time=None,
                              max_dec_len_this_time=None, rope_emb=None,
                              mask=None, tgt_mask=None, max_seq_len=-1,
                              block_size=64, use_neox_style=False,
                              use_dynamic_cachekv_quant=False,
                              quant_round_type=1, quant_max_bound=127.0,
                              quant_min_bound=-127.0, out_scale=-1,
                              compute_dtype="default"):
    """Paged (block) KV-cache attention for batched serving (reference:
    incubate/nn/functional/block_multihead_attention.py:30 over
    block_multi_head_attention_kernel.cu).

    qkv: [token_num, (nh + 2*kv_nh)*hd] unpadded tokens; caches
    [max_block_num, kv_nh, block_size, hd]; block_tables [b, pages_per_seq].
    Per sequence: encoder rows (seq_lens_encoder > 0) prefill — K/V scattered
    into their pages and causal self-attention over the prompt; decoder rows
    (seq_lens_decoder > 0, one token this time) append at position
    seq_lens_decoder[i] and run the paged decode kernel
    (ops/paged_attention.py) over the whole cache. Returns
    (out [token_num, nh*hd], qkv, key_cache, value_cache); caches are also
    updated in place (reference contract).

    In-op rope: ``rope_emb`` [2, batch, max_seq, 1, head_size//2] (cos;sin,
    reference layout) is applied to q and the new k at each token's absolute
    position BEFORE the cache append, in ``use_neox_style`` or interleaved
    form.

    Int8 KV cache: with int8 caches + static per-kv-head
    ``cache_*_quant_scales``/``cache_*_dequant_scales`` [kv_heads], new K/V
    quantize on append and the decode path dequantizes EXACTLY (per-head
    scales commute with online softmax: the K scale folds into q per head
    before the paged kernel, the V scale folds into its output — the kernel's
    VMEM loop reads int8 pages directly, halving cache HBM). Dynamic
    (per-batch) quant scales are not supported."""
    if any(t is not None for t in (qkv_out_scale, out_shift, out_smooth,
                                   pre_key_cache, pre_value_cache)):
        raise NotImplementedError(
            "block_multihead_attention: qkv/out smooth-quant and pre-cache")
    all_scales = (cache_k_quant_scales, cache_v_quant_scales,
                  cache_k_dequant_scales, cache_v_dequant_scales)
    quant = any(t is not None for t in all_scales)
    if use_dynamic_cachekv_quant:
        raise NotImplementedError(
            "block_multihead_attention: dynamic (per-batch) cache-kv quant — "
            "use static per-head scales")
    if quant and any(t is None for t in all_scales):
        raise ValueError("cache quant needs all four k/v quant/dequant scales")
    import numpy as np

    from ....core.tensor import Tensor, unwrap
    from ....ops.flash_attention import flash_attention
    from ....ops.paged_attention import append_paged_kv, paged_decode_attention

    qkv_arr = unwrap(qkv)
    kc = unwrap(key_cache)
    vc = unwrap(value_cache)
    tables = unwrap(block_tables).astype(jnp.int32)
    enc = np.asarray(unwrap(seq_lens_encoder)).reshape(-1)
    dec = np.asarray(unwrap(seq_lens_decoder)).reshape(-1)
    this_time = np.asarray(unwrap(seq_lens_this_time)).reshape(-1)
    b = enc.shape[0]
    kv_nh, hd = kc.shape[1], kc.shape[3]
    nh = qkv_arr.shape[-1] // hd - 2 * kv_nh
    group = nh // kv_nh

    starts = np.concatenate([[0], np.cumsum(this_time)])
    qkv3 = qkv_arr.reshape(-1, nh + 2 * kv_nh, hd)
    if qkv_bias is not None:
        qkv3 = qkv3 + unwrap(qkv_bias).reshape(1, nh + 2 * kv_nh, hd)
    q_tok = qkv3[:, :nh]                   # [tokens, nh, hd]
    k_tok = qkv3[:, nh:nh + kv_nh]
    v_tok = qkv3[:, nh + kv_nh:]

    seq_ids = np.repeat(np.arange(b), this_time).astype(np.int32)
    pos_in_seq = np.concatenate(
        [np.arange(t) + (dec[i] if dec[i] > 0 else 0)
         for i, t in enumerate(this_time)]).astype(np.int32) if len(seq_ids) else np.zeros(0, np.int32)

    if rope_emb is not None:
        # [2, b, max_seq, 1, hd//2] -> per-token cos/sin at absolute position
        rot = unwrap(rope_emb).astype(jnp.float32)
        if rot.shape[0] != 2 or rot.shape[1] not in (1, b):
            raise ValueError(
                "rope_emb must be [2, batch (or 1), max_seq, 1, head//2] "
                f"(cos;sin), got shape {rot.shape}")
        if rot.shape[1] == 1 and b > 1:
            rot = jnp.broadcast_to(rot, (2, b) + rot.shape[2:])
        rot = rot.reshape(2, rot.shape[1], -1, rot.shape[-1])
        if len(pos_in_seq) and int(pos_in_seq.max()) >= rot.shape[2]:
            raise ValueError(
                f"rope_emb covers {rot.shape[2]} positions but a token sits "
                f"at position {int(pos_in_seq.max())} — fancy-index clamping "
                "would silently reuse the last row's rotation")
        sid = jnp.asarray(seq_ids)
        posj = jnp.asarray(pos_in_seq)
        cos_t, sin_t = rot[0][sid, posj], rot[1][sid, posj]   # [tokens, hd//2]
        cos_t, sin_t = _expand_rope_tables(cos_t, sin_t, hd, use_neox_style)
        qf, kf = _rope_pair(q_tok.astype(jnp.float32),
                            k_tok.astype(jnp.float32),
                            cos_t[:, None, :], sin_t[:, None, :],
                            use_neox_style)
        q_tok, k_tok = qf.astype(q_tok.dtype), kf.astype(k_tok.dtype)

    # scatter every new token's K/V into its sequence's pages
    if quant:
        ks_q = unwrap(cache_k_quant_scales).astype(jnp.float32)
        vs_q = unwrap(cache_v_quant_scales).astype(jnp.float32)
        k_store = _quant_cache(k_tok, ks_q, quant_round_type,
                               quant_max_bound, quant_min_bound)
        v_store = _quant_cache(v_tok, vs_q, quant_round_type,
                               quant_max_bound, quant_min_bound)
        ks_d = unwrap(cache_k_dequant_scales).astype(jnp.float32)
        vs_d = unwrap(cache_v_dequant_scales).astype(jnp.float32)
    else:
        k_store, v_store = k_tok.astype(kc.dtype), v_tok.astype(vc.dtype)
    kc, vc = append_paged_kv(kc, vc, k_store, v_store, tables,
                             jnp.asarray(pos_in_seq), jnp.asarray(seq_ids))

    out = jnp.zeros((qkv3.shape[0], nh, hd), qkv_arr.dtype)

    # ---- decode rows: ONE batched paged-kernel call (the serving hot path)
    dec_rows = np.nonzero((dec > 0) & (this_time == 1))[0]
    if len(dec_rows):
        ridx = jnp.asarray(dec_rows, jnp.int32)
        tok_idx = jnp.asarray(starts[dec_rows], jnp.int32)
        qd = q_tok[tok_idx]                             # [n, nh, hd]
        ctx = jnp.asarray(dec[dec_rows] + 1, jnp.int32)
        if quant:
            # static per-kv-head scales commute with online softmax: K dequant
            # folds into q (s = (q*ks)·k_int8), V dequant into the output
            # (out = (Σp·v_int8/l)·vs) — the kernel streams int8 pages
            qd = qd * jnp.repeat(ks_d, group)[None, :, None].astype(qd.dtype)
        od = paged_decode_attention(qd, kc, vc, tables[ridx], ctx)
        if quant:
            od = od * jnp.repeat(vs_d, group)[None, :, None].astype(od.dtype)
        out = out.at[tok_idx].set(od.astype(out.dtype))

    # ---- prefill rows (enc > 0) AND multi-token continuations (dec > 0 with
    # several tokens this time — chunked prefill / speculative decode): the
    # chunk attends the row's whole cache prefix + itself, end-aligned causal
    from ....ops.paged_attention import gather_paged_kv

    page = kc.shape[2]
    chunk_rows = np.nonzero((enc > 0) | ((dec > 0) & (this_time > 1)))[0]
    for i in chunk_rows:
        s0, s1 = int(starts[i]), int(starts[i + 1])
        n_new = s1 - s0
        prefix = int(dec[i]) if dec[i] > 0 else 0
        ctx = prefix + n_new
        qp = q_tok[s0:s1][None]                          # [1, s, nh, hd]
        if prefix:
            # pages already hold prefix + the newly scattered chunk
            kg, vg = gather_paged_kv(kc, vc, tables[i:i + 1],
                                     tables.shape[1] * page)
            kp, vp = kg[:, :ctx], vg[:, :ctx]
            if quant:
                kp = (kp.astype(jnp.float32)
                      * ks_d.reshape(1, 1, -1, 1)).astype(q_tok.dtype)
                vp = (vp.astype(jnp.float32)
                      * vs_d.reshape(1, 1, -1, 1)).astype(q_tok.dtype)
        else:
            kp, vp = k_tok[s0:s1][None], v_tok[s0:s1][None]
        if mask is not None:
            # mask path: dense fallback honoring the provided bias
            m = unwrap(mask)[i, :, :n_new, :ctx][None]
            logits = jnp.einsum("bqnh,bknh->bnqk", qp.astype(jnp.float32),
                                jnp.repeat(kp, group, 2).astype(jnp.float32))
            logits = logits * (hd ** -0.5) + m.astype(jnp.float32)
            probs = jax.nn.softmax(logits, -1)
            op = jnp.einsum("bnqk,bknh->bqnh", probs,
                            jnp.repeat(vp, group, 2).astype(jnp.float32))[0]
        else:
            op = flash_attention(qp, kp, vp, causal=True)[0]
        out = out.at[s0:s1].set(op.astype(out.dtype))

    out = out.reshape(-1, nh * hd)
    key_cache._data = kc    # reference in-place cache contract
    value_cache._data = vc
    return (Tensor(out), qkv if isinstance(qkv, Tensor) else Tensor(qkv_arr),
            key_cache, value_cache)


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """matmul + bias in one XLA fusion (reference: incubate fused_matmul_bias
    over cublasLt epilogue — XLA fuses the add natively)."""

    def fn(a, w, *b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            w = jnp.swapaxes(w, -1, -2)
        out = jnp.matmul(a, w)
        return out + b[0] if b else out

    if bias is not None:
        return apply_fn("fused_matmul_bias", fn, x, y, bias)
    return apply_fn("fused_matmul_bias", fn, x, y)


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.5, ln_epsilon=1e-5,
                                           training=True, mode="upscale_in_train",
                                           name=None):
    """(x + bias) -> dropout -> + residual -> LayerNorm, one fusion
    (reference: incubate/nn/functional/fused_bias_dropout_residual_layer_norm)."""
    from ....nn import functional as F
    from ....tensor import add as t_add

    h = x if bias is None else apply_fn("bias_add", lambda a, b: a + b, x, bias)
    h = F.dropout(h, p=dropout_rate, training=training, mode=mode)
    h = t_add(h, residual)
    return F.layer_norm(h, h.shape[-1:], weight=ln_scale, bias=ln_bias,
                        epsilon=ln_epsilon)


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights, qkv_biases,
                            linear_weights, linear_biases, ffn_ln_scales,
                            ffn_ln_biases, ffn1_weights, ffn1_biases,
                            ffn2_weights, ffn2_biases, pre_layer_norm=True,
                            epsilon=1e-5, cache_kvs=None, time_step=None,
                            attn_mask=None, dropout_rate=0.0, activation="gelu",
                            training=False, mode="upscale_in_train",
                            trans_qkvw=True, ring_id=-1, num_heads=None,
                            name=None):
    """Stacked pre-LN transformer layers in one call (reference:
    incubate/nn/functional/fused_multi_transformer — the GPT inference
    megakernel). Each layer: LN -> qkv -> MHA -> proj -> +res -> LN -> FFN.
    XLA fuses the whole unrolled chain into one program.

    ``num_heads`` is required (the reference reads it from the qkv weight's
    4-D [3, nh, hd, h] layout; the 2-D layout here cannot infer it safely).

    Incremental decoding: ``cache_kvs`` is a per-layer list of dense caches
    [2, b, nh, max_seq, hd]. ``time_step=None`` prefills (writes positions
    0..s-1); an integer/Tensor time_step decodes at that position attending
    over the whole cache prefix. Caches update in place (reference contract)."""
    from ....core.tensor import unwrap
    from ....nn import functional as F
    from ....tensor import add, reshape, split

    import numpy as np

    if num_heads is None:
        raise ValueError("fused_multi_transformer requires num_heads")
    pos0 = 0
    if time_step is not None:
        pos0 = (time_step if isinstance(time_step, int)
                else int(np.asarray(unwrap(time_step)).reshape(-1)[0]))

    def _drop(t):
        if dropout_rate and training:
            return F.dropout(t, p=dropout_rate, training=True, mode=mode)
        return t

    h = x
    for i in range(len(qkv_weights)):
        res = h
        a_in = h
        if pre_layer_norm:
            a_in = F.layer_norm(h, h.shape[-1:], weight=ln_scales[i],
                                bias=ln_biases[i], epsilon=epsilon)
        qkv = fused_matmul_bias(a_in, qkv_weights[i], qkv_biases[i],
                                transpose_y=trans_qkvw)
        d = qkv.shape[-1] // 3
        nh = num_heads
        hd = d // nh
        q, k, v = split(qkv, 3, axis=-1)
        b, s = q.shape[0], q.shape[1]
        q4 = reshape(q, [b, s, nh, hd])
        k4 = reshape(k, [b, s, nh, hd])
        v4 = reshape(v, [b, s, nh, hd])
        if cache_kvs is not None:
            # write this chunk at positions pos0..pos0+s-1, attend over the
            # whole prefix (end-aligned causal handles kv_len > q_len)
            cache = unwrap(cache_kvs[i])                  # [2, b, nh, max, hd]
            knew = jnp.swapaxes(unwrap(k4), 1, 2)         # [b, nh, s, hd]
            vnew = jnp.swapaxes(unwrap(v4), 1, 2)
            kc = jax.lax.dynamic_update_slice(
                cache[0], knew.astype(cache.dtype), (0, 0, pos0, 0))
            vc = jax.lax.dynamic_update_slice(
                cache[1], vnew.astype(cache.dtype), (0, 0, pos0, 0))
            cache_kvs[i]._data = jnp.stack([kc, vc])      # in-place contract
            from ....core.tensor import Tensor as _T
            k4 = _T(jnp.swapaxes(kc[:, :, : pos0 + s], 1, 2))
            v4 = _T(jnp.swapaxes(vc[:, :, : pos0 + s], 1, 2))
        attn = F.scaled_dot_product_attention(
            q4, k4, v4, attn_mask=attn_mask,
            is_causal=attn_mask is None)
        out = _drop(fused_matmul_bias(reshape(attn, [b, s, d]),
                                      linear_weights[i], linear_biases[i]))
        h = add(res, out)
        if not pre_layer_norm:  # post-LN: normalize AFTER the residual add
            h = F.layer_norm(h, h.shape[-1:], weight=ln_scales[i],
                             bias=ln_biases[i], epsilon=epsilon)
        res2 = h
        f_in = h
        if pre_layer_norm:
            f_in = F.layer_norm(h, h.shape[-1:], weight=ffn_ln_scales[i],
                                bias=ffn_ln_biases[i], epsilon=epsilon)
        f1 = fused_matmul_bias(f_in, ffn1_weights[i], ffn1_biases[i])
        f1 = F.gelu(f1) if activation == "gelu" else F.relu(f1)
        h = add(res2, _drop(fused_matmul_bias(f1, ffn2_weights[i],
                                              ffn2_biases[i])))
        if not pre_layer_norm:
            h = F.layer_norm(h, h.shape[-1:], weight=ffn_ln_scales[i],
                             bias=ffn_ln_biases[i], epsilon=epsilon)
    return h
