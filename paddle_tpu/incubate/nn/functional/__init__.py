"""incubate.nn.functional — fused ops (reference: python/paddle/incubate/nn/functional).

TPU-native: most of the reference's 75 fused CUDA kernels
(phi/kernels/fusion/gpu) are XLA fusions here — the functions below express the
fused computation as one traced region; XLA emits a single TPU kernel chain.
Attention variants route to the Pallas flash kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ....core.op_registry import apply_fn
from ....core.tensor import Tensor
from ....nn import functional as F


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6, begin_norm_axis=-1,
                   bias=None, residual=None, quant_scale=-1, **kw):
    """Reference: incubate/nn/functional/fused_rms_norm.py."""

    def fn(a, w, *rest):
        i = 0
        res = None
        if residual is not None:
            res = rest[i]
            i += 1
        if res is not None:
            a = a + res
        dt = a.dtype
        af = a.astype(jnp.float32)
        ms = jnp.mean(jnp.square(af), axis=-1, keepdims=True)
        out = (af * jax.lax.rsqrt(ms + epsilon)).astype(dt) * w
        if norm_bias is not None:
            out = out + rest[i]
        return out

    args = [x, norm_weight] + [t for t in (residual, norm_bias) if t is not None]
    return apply_fn("fused_rms_norm", fn, *args)


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5, begin_norm_axis=-1,
                     bias=None, residual=None, **kw):
    if residual is not None:
        x = x + residual
    return F.layer_norm(x, x.shape[begin_norm_axis:] if begin_norm_axis != -1 else [x.shape[-1]],
                        norm_weight, norm_bias, epsilon)


def swiglu(x, y=None, name=None):
    """Reference: incubate/nn/functional/swiglu.py — silu(x) * y (fused gate)."""
    if y is None:
        def fn(a):
            a1, a2 = jnp.split(a, 2, axis=-1)
            return jax.nn.silu(a1) * a2

        return apply_fn("swiglu", fn, x)
    return apply_fn("swiglu", lambda a, b: jax.nn.silu(a) * b, x, y)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0):
    """Reference: incubate/nn/functional/fused_rotary_position_embedding.py.
    q/k/v layout: [batch, seq, heads, head_dim]."""

    def rope_one(t, s, c):
        if use_neox_rotary_style:
            d = t.shape[-1]
            t1, t2 = t[..., : d // 2], t[..., d // 2:]
            rot = jnp.concatenate([-t2, t1], axis=-1)
            return t * c + rot * s
        t1 = t[..., 0::2]
        t2 = t[..., 1::2]
        rot = jnp.stack([-t2, t1], axis=-1).reshape(t.shape)
        return t * c + rot * s

    def make_sincos(seq_len, dim, dtype):
        inv = 1.0 / (rotary_emb_base ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
        tpos = jnp.arange(seq_len, dtype=jnp.float32)
        freqs = jnp.outer(tpos, inv)
        if use_neox_rotary_style:
            emb = jnp.concatenate([freqs, freqs], axis=-1)
        else:
            emb = jnp.repeat(freqs, 2, axis=-1)
        return jnp.sin(emb).astype(dtype)[None, :, None, :], jnp.cos(emb).astype(dtype)[None, :, None, :]

    outs = []
    tensors = [t for t in (q, k, v) if t is not None]

    def fn(*arrs):
        rest = list(arrs)
        n_t = len(tensors)
        main = rest[:n_t]
        extra = rest[n_t:]
        if sin is not None:
            s, c = extra[0], extra[1]
            if s.ndim == 2:
                s = s[None, :, None, :]
                c = c[None, :, None, :]
            elif s.ndim == 4 and s.shape[2] != 1 and s.shape[1] != main[0].shape[1]:
                pass
        else:
            s, c = make_sincos(main[0].shape[1], main[0].shape[-1], main[0].dtype)
        if position_ids is not None:
            pid = extra[-1]
            s = jnp.take(s[0, :, 0, :], pid, axis=0)[:, :, None, :]
            c = jnp.take(c[0, :, 0, :], pid, axis=0)[:, :, None, :]
        return tuple(rope_one(t, s, c) for t in main)

    args = tensors + [t for t in (sin, cos) if t is not None]
    if position_ids is not None:
        args = args + [position_ids]
    res = apply_fn("fused_rope", fn, *args)
    res = list(res) if isinstance(res, tuple) else [res]
    out = []
    i = 0
    for t in (q, k, v):
        if t is not None:
            out.append(res[i])
            i += 1
        else:
            out.append(None)
    return tuple(out)


def fused_bias_act(x, bias=None, act_method="gelu", **kw):
    if bias is not None:
        x = x + bias
    return getattr(F, act_method)(x)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    def fn(a, w, *b):
        ww = w.T if transpose_weight else w
        out = jnp.matmul(a, ww)
        if b:
            out = out + b[0]
        return out

    args = [x, weight] + ([bias] if bias is not None else [])
    return apply_fn("fused_linear", fn, *args)


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False, activation="gelu"):
    def fn(a, w, b):
        if trans_x:
            a = a.T
        if trans_y:
            w = w.T
        return getattr(jax.nn, activation if activation != "none" else "identity",
                       lambda v: v)(jnp.matmul(a, w) + b)

    return apply_fn("fused_linear_activation", fn, x, y, bias)


def fused_moe(x, gate_weight, ffn1_weight, ffn2_weight, ffn1_bias=None,
              ffn1_scale=None, ffn2_bias=None, ffn2_scale=None,
              quant_method="None", moe_topk=2, norm_topk_prob=True,
              capacity_factor=None):
    """Reference: incubate/nn/functional/fused_moe.py:22 — same signature.

    x: [bsz, seq, d_model]; gate_weight: per-token gate logits
    [bsz, seq, num_experts]; ffn1_weight: [E, d_model, d_ff*2] (gated — swiglu
    split) or [E, d_model, d_ff] (plain gelu); ffn2_weight: [E, d_ff, d_model];
    biases [E, 1, d] or [E, d]. quant_method/scales unsupported (as in the
    reference's CPU path).

    One traced region: topk dispatch + batched expert FFN + combine (shared
    routing in incubate.distributed.models.moe). Deviation from the CUDA
    kernel: a dense-dispatch capacity bounds expert buffers; the default
    ``capacity_factor=None`` sets capacity = num tokens (NO token drops, exact
    reference semantics) — pass e.g. 1.25 to bound memory on long sequences.
    """
    import math

    if quant_method != "None" or ffn1_scale is not None or ffn2_scale is not None:
        raise NotImplementedError("fused_moe quantization is not supported")

    from ...distributed.models.moe.moe_layer import routed_ffn

    def fn(x, gate_logits, w1, w2, b1, b2):
        orig = x.shape
        d_model = orig[-1]
        tokens = x.reshape(-1, d_model)
        n, e = tokens.shape[0], w1.shape[0]
        probs = jax.nn.softmax(
            gate_logits.reshape(-1, e).astype(jnp.float32), axis=-1)
        if capacity_factor is None:
            cap = n
        else:
            cap = max(int(math.ceil(n * moe_topk * capacity_factor / e)), moe_topk)
        gated = w1.shape[-1] == 2 * w2.shape[-2]

        def expert_fn(expert_in):
            h = jnp.einsum("ecd,edm->ecm", expert_in, w1)
            if b1 is not None:
                h = h + b1.reshape(e, 1, -1)
            if gated:
                half = h.shape[-1] // 2
                h = jax.nn.silu(h[..., :half]) * h[..., half:]
            else:
                h = jax.nn.gelu(h)
            out = jnp.einsum("ecm,emd->ecd", h, w2)
            if b2 is not None:
                out = out + b2.reshape(e, 1, -1)
            return out

        y, _ = routed_ffn(tokens, probs, expert_fn, moe_topk, cap,
                          renormalize=norm_topk_prob)
        return y.astype(x.dtype).reshape(orig)

    return apply_fn("fused_moe", fn, x, gate_weight, ffn1_weight, ffn2_weight,
                    ffn1_bias, ffn2_bias)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train", name=None):
    return F.dropout(x, p, training=training, mode=mode) + y


def fused_multi_head_attention(x, qkv_weight, linear_weight, pre_layer_norm=False, **kw):
    raise NotImplementedError("use nn.MultiHeadAttention (XLA/Pallas fused) — tracked in docs/PARITY.md")


def fused_feedforward(x, linear1_weight, linear2_weight, **kw):
    raise NotImplementedError("XLA fuses nn.Linear+act+Linear chains natively — tracked in docs/PARITY.md")


def masked_multihead_attention(x, cache_kv=None, **kw):
    raise NotImplementedError("decode-time MHA lands with the serving suite — see ops/paged_attention")


def variable_length_memory_efficient_attention(q, k, v, seq_lens=None, kv_seq_lens=None, mask=None, scale=None, causal=False):
    return F.scaled_dot_product_attention(q, k, v, attn_mask=mask, is_causal=causal)


def block_multihead_attention(*args, **kw):
    raise NotImplementedError("paged/block KV attention: ops/paged_attention (serving suite)")


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """matmul + bias in one XLA fusion (reference: incubate fused_matmul_bias
    over cublasLt epilogue — XLA fuses the add natively)."""

    def fn(a, w, *b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            w = jnp.swapaxes(w, -1, -2)
        out = jnp.matmul(a, w)
        return out + b[0] if b else out

    if bias is not None:
        return apply_fn("fused_matmul_bias", fn, x, y, bias)
    return apply_fn("fused_matmul_bias", fn, x, y)


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.5, ln_epsilon=1e-5,
                                           training=True, mode="upscale_in_train",
                                           name=None):
    """(x + bias) -> dropout -> + residual -> LayerNorm, one fusion
    (reference: incubate/nn/functional/fused_bias_dropout_residual_layer_norm)."""
    from ....nn import functional as F
    from ....tensor import add as t_add

    h = x if bias is None else apply_fn("bias_add", lambda a, b: a + b, x, bias)
    h = F.dropout(h, p=dropout_rate, training=training, mode=mode)
    h = t_add(h, residual)
    return F.layer_norm(h, h.shape[-1:], weight=ln_scale, bias=ln_bias,
                        epsilon=ln_epsilon)


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights, qkv_biases,
                            linear_weights, linear_biases, ffn_ln_scales,
                            ffn_ln_biases, ffn1_weights, ffn1_biases,
                            ffn2_weights, ffn2_biases, pre_layer_norm=True,
                            epsilon=1e-5, cache_kvs=None, time_step=None,
                            attn_mask=None, dropout_rate=0.0, activation="gelu",
                            training=False, mode="upscale_in_train",
                            trans_qkvw=True, ring_id=-1, num_heads=None,
                            name=None):
    """Stacked pre-LN transformer layers in one call (reference:
    incubate/nn/functional/fused_multi_transformer — the GPT inference
    megakernel). Each layer: LN -> qkv -> MHA -> proj -> +res -> LN -> FFN.
    XLA fuses the whole unrolled chain into one program.

    ``num_heads`` is required (the reference reads it from the qkv weight's
    4-D [3, nh, hd, h] layout; the 2-D layout here cannot infer it safely).
    Incremental decoding (cache_kvs/time_step) is not implemented."""
    from ....nn import functional as F
    from ....tensor import add, reshape, split

    if cache_kvs is not None or time_step is not None:
        raise NotImplementedError(
            "fused_multi_transformer: cache_kvs/time_step (incremental "
            "decoding) not supported — use the model-level kv-cache path")
    if num_heads is None:
        raise ValueError("fused_multi_transformer requires num_heads")

    def _drop(t):
        if dropout_rate and training:
            return F.dropout(t, p=dropout_rate, training=True, mode=mode)
        return t

    h = x
    for i in range(len(qkv_weights)):
        res = h
        a_in = h
        if pre_layer_norm:
            a_in = F.layer_norm(h, h.shape[-1:], weight=ln_scales[i],
                                bias=ln_biases[i], epsilon=epsilon)
        qkv = fused_matmul_bias(a_in, qkv_weights[i], qkv_biases[i],
                                transpose_y=trans_qkvw)
        d = qkv.shape[-1] // 3
        nh = num_heads
        hd = d // nh
        q, k, v = split(qkv, 3, axis=-1)
        b, s = q.shape[0], q.shape[1]
        attn = F.scaled_dot_product_attention(
            reshape(q, [b, s, nh, hd]), reshape(k, [b, s, nh, hd]),
            reshape(v, [b, s, nh, hd]), attn_mask=attn_mask,
            is_causal=attn_mask is None)
        out = _drop(fused_matmul_bias(reshape(attn, [b, s, d]),
                                      linear_weights[i], linear_biases[i]))
        h = add(res, out)
        if not pre_layer_norm:  # post-LN: normalize AFTER the residual add
            h = F.layer_norm(h, h.shape[-1:], weight=ln_scales[i],
                             bias=ln_biases[i], epsilon=epsilon)
        res2 = h
        f_in = h
        if pre_layer_norm:
            f_in = F.layer_norm(h, h.shape[-1:], weight=ffn_ln_scales[i],
                                bias=ffn_ln_biases[i], epsilon=epsilon)
        f1 = fused_matmul_bias(f_in, ffn1_weights[i], ffn1_biases[i])
        f1 = F.gelu(f1) if activation == "gelu" else F.relu(f1)
        h = add(res2, _drop(fused_matmul_bias(f1, ffn2_weights[i],
                                              ffn2_biases[i])))
        if not pre_layer_norm:
            h = F.layer_norm(h, h.shape[-1:], weight=ffn_ln_scales[i],
                             bias=ffn_ln_biases[i], epsilon=epsilon)
    return h
