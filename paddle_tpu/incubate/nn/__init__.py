"""incubate.nn — fused-op layer API (reference: python/paddle/incubate/nn)."""

from . import functional  # noqa: F401
