"""Mixture-of-Experts layer with expert parallelism over the ``ep`` mesh axis.

Parity anchor: /root/reference/python/paddle/incubate/distributed/models/moe/
moe_layer.py:263 ``MoELayer`` (gates gshard/switch/naive, alltoall dispatch via
``global_scatter``/``global_gather`` utils.py:32, MoE grad clip).

TPU-native redesign: the reference scatters tokens with index_select + NCCL
alltoall (dynamic shapes). Here routing is the GShard dense-einsum formulation —
dispatch/combine one-hot tensors with a static per-expert ``capacity`` — and the
expert FFN is ONE batched computation over stacked weights ``[E, ...]`` sharded
over the ``ep`` mesh axis ("expert" logical axis). When tokens are sharded over
dp/fsdp and experts over ep, GSPMD lowers the dispatch einsum to cross-device
dispatch collectives riding ICI (measured: all-reduce of per-expert partials —
the role of the reference's hand-issued alltoall; docs/MOE_AB.md).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from .....core.tensor import Tensor
from .....distributed.auto_parallel.logical_sharding import annotate, constrain
from .....nn import initializer as I
from .....nn.layer.layers import Layer
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate


def _raw(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def routed_ffn(tokens, probs, expert_fn, k: int, capacity: int,
               renormalize: bool = True, dispatch_mode: str = "auto"):
    """Shared dispatch → expert_fn → combine pipeline on raw arrays.

    tokens: [n, d]; probs: [n, E]; expert_fn: [E, C, d] -> [E, C, d'].
    Returns (out [n, d'], aux_loss). Used by MoELayer and fused_moe so the
    routing/capacity semantics exist exactly once.

    dispatch_mode:
      - "einsum": GShard dense one-hot dispatch/combine — O(n*E*C*d) MXU
        work; GSPMD inserts the ep dispatch collectives when tokens are
        dp-sharded and experts ep-sharded (docs/MOE_AB.md). Fine for few
        experts.
      - "scatter": sparse dispatch via segment-sum scatter + gather —
        O(n*k*d), the sorted/ragged-dispatch regime for MANY experts
        (VERDICT r3 weak #8; capacity guarantees each (expert, slot) gets
        at most one token, so the scatter is collision-free).
      - "ragged": sort tokens by expert and run the expert FFN as grouped
        matmuls (megablox gmm kernel on TPU, ``jax.lax.ragged_dot``
        elsewhere) — NO capacity padding and no
        [E, C, d] staging buffers in HBM (megablocks-class dropless
        semantics: every token reaches its top-k experts; ``capacity`` is
        ignored). Single-device / non-ep-sharded regime: under an ep mesh
        axis use einsum/scatter, whose dispatch GSPMD turns into the
        all_to_all. Requires ``expert_fn.forward_ragged``; falls back to
        scatter otherwise.
      - "auto": scatter when the dense one-hot buffers [n, E, C] would be
        large (> 16M elements — note C grows with n, so the einsum blows up
        quadratically in TOKEN count, independent of E) or when E >= 16.
    """
    from .gate import _load_balance_loss, topk_dispatch, topk_routing

    n, d = tokens.shape
    e = probs.shape[-1]
    if dispatch_mode == "auto":
        dispatch_mode = ("scatter" if e >= 16 or n * e * capacity > (1 << 24)
                         else "einsum")
    if (dispatch_mode in ("ragged", "pgmm")
            and getattr(expert_fn, "forward_" + dispatch_mode, None) is None):
        dispatch_mode = "scatter"
    if dispatch_mode in ("ragged", "pgmm"):
        # dropless top-k (no capacity): shared routing for both grouped paths
        w, eidx = jax.lax.top_k(probs, k)                        # [n, k]
        if renormalize and k > 1:
            w = w / jnp.maximum(w.sum(-1, keepdims=True), 1e-9)
        aux = _load_balance_loss(
            probs, jax.nn.one_hot(eidx[:, 0], e, dtype=probs.dtype))
        flat_e = eidx.reshape(-1)                                # [n*k]
        if dispatch_mode == "pgmm":
            # Pallas padded grouped matmul: tile-aligned sorted layout
            from .....ops.grouped_matmul import padded_group_layout

            order, pos_sorted, tile_gids, p_total = padded_group_layout(
                flat_e, e, n * k)
            sorted_tokens = jnp.take(tokens, order // k, axis=0)
            x_pad = jnp.zeros((p_total, d), tokens.dtype).at[pos_sorted].set(
                sorted_tokens)
            out_pad = _raw(expert_fn.forward_pgmm(x_pad, tile_gids))
            out_sorted = jnp.take(out_pad, pos_sorted, axis=0)   # [n*k, d2]
        else:
            order = jnp.argsort(flat_e, stable=True)
            sorted_tokens = jnp.take(tokens, order // k, axis=0)  # [n*k, d]
            group_sizes = jax.ops.segment_sum(
                jnp.ones_like(flat_e), flat_e,
                num_segments=e).astype(jnp.int32)
            out_sorted = _raw(expert_fn.forward_ragged(
                sorted_tokens, group_sizes, jnp.take(flat_e, order)))
        inv = jnp.argsort(order, stable=True)
        out_flat = jnp.take(out_sorted, inv, axis=0).reshape(n, k, -1)
        out = jnp.einsum("nk,nkd->nd", w.astype(tokens.dtype), out_flat)
        return out, aux
    if dispatch_mode == "einsum":
        combine, dispatch, aux = topk_dispatch(probs, k, capacity, renormalize)
        expert_in = jnp.einsum("nec,nd->ecd", dispatch.astype(tokens.dtype),
                               tokens)
        expert_in = constrain(expert_in, "expert", None, "embed")
        expert_out = _raw(expert_fn(expert_in))
        out = jnp.einsum("nec,ecd->nd", combine.astype(tokens.dtype),
                         expert_out)
        return out, aux
    if dispatch_mode != "scatter":
        raise ValueError(f"dispatch_mode must be auto/einsum/scatter/ragged/"
                         f"pgmm, got {dispatch_mode!r}")
    eidx, cpos, w, keep, aux = topk_routing(probs, k, capacity, renormalize)
    slot = (eidx * capacity + cpos).reshape(-1)                  # [n*k]
    kf = keep.astype(tokens.dtype).reshape(n * k, 1)
    # dropped choices carry kf=0 (no contribution) and w=0 (no combine);
    # their clamped slot ids are harmless
    contrib = jnp.broadcast_to(tokens[:, None, :], (n, k, d)).reshape(n * k, d)
    expert_in = jax.ops.segment_sum(contrib * kf, slot,
                                    num_segments=e * capacity)
    expert_in = constrain(expert_in.reshape(e, capacity, d),
                          "expert", None, "embed")
    expert_out = _raw(expert_fn(expert_in))
    d2 = expert_out.shape[-1]
    gathered = jnp.take(expert_out.reshape(e * capacity, d2), slot,
                        axis=0).reshape(n, k, d2)
    wk = (w * keep.astype(w.dtype)).astype(tokens.dtype)
    out = jnp.einsum("nk,nkd->nd", wk, gathered)
    return out, aux


class ExpertFFN(Layer):
    """Stacked per-expert FFN: weights carry a leading "expert" logical axis."""

    def __init__(self, num_experts: int, d_model: int, d_hidden: int,
                 activation: str = "gelu", dtype: str = "float32",
                 initializer_range: float = 0.02):
        super().__init__()
        self.num_experts = num_experts
        self.activation = activation
        init = I.Normal(std=initializer_range)
        self.w1 = annotate(
            self.create_parameter([num_experts, d_model, d_hidden], dtype=dtype,
                                  default_initializer=init),
            "expert", "embed", "expert_mlp")
        self.b1 = annotate(
            self.create_parameter([num_experts, d_hidden], dtype=dtype, is_bias=True),
            "expert", "expert_mlp")
        self.w2 = annotate(
            self.create_parameter([num_experts, d_hidden, d_model], dtype=dtype,
                                  default_initializer=init),
            "expert", "expert_mlp", "embed")
        self.b2 = annotate(
            self.create_parameter([num_experts, d_model], dtype=dtype, is_bias=True),
            "expert", "embed")

    def forward(self, x):
        """x: [E, C, d_model] — batched over the (ep-sharded) expert dim."""
        x = _raw(x)
        h = jnp.einsum("ecd,edm->ecm", x, self.w1._data) + self.b1._data[:, None, :]
        h = constrain(h, "expert", None, "expert_mlp")
        h = self._act(h)
        out = jnp.einsum("ecm,emd->ecd", h, self.w2._data) + self.b2._data[:, None, :]
        return constrain(out, "expert", None, "embed")

    def _act(self, h):
        if self.activation == "gelu":
            return jax.nn.gelu(h)
        if self.activation == "relu":
            return jax.nn.relu(h)
        if self.activation == "silu":
            return jax.nn.silu(h)
        raise ValueError(f"unknown activation {self.activation}")

    def forward_ragged(self, x, group_sizes, expert_ids):
        """Dropless grouped-matmul path (routed_ffn dispatch_mode="ragged"):
        x [m, d] sorted by expert, group_sizes [E] int32 row counts,
        expert_ids [m] the per-row expert (for the biases)."""
        from .....ops.grouped_matmul import grouped_dot

        x = _raw(x)
        h = grouped_dot(x, self.w1._data, group_sizes)
        h = self._act(h + jnp.take(self.b1._data, expert_ids, axis=0))
        out = grouped_dot(h, self.w2._data, group_sizes)
        return out + jnp.take(self.b2._data, expert_ids, axis=0)

    def forward_pgmm(self, x_pad, tile_gids, tile_m=None, interpret=False):
        """Pallas padded-grouped-matmul path (dispatch_mode="pgmm"); per-row
        biases follow the tile's expert id (pad rows get a bias too, but
        their outputs are never gathered back)."""
        from .....ops.grouped_matmul import TILE_M, pgmm

        tile_m = tile_m or TILE_M
        x_pad = _raw(x_pad)
        row_e = jnp.repeat(tile_gids, tile_m)
        h = pgmm(x_pad, self.w1._data, tile_gids, tile_m, interpret)
        h = self._act(h + jnp.take(self.b1._data, row_e, axis=0))
        out = pgmm(h, self.w2._data, tile_gids, tile_m, interpret)
        return out + jnp.take(self.b2._data, row_e, axis=0)


class SwiGLUExpertFFN(Layer):
    """Llama/Mixtral-style gated experts (swiglu), stacked over the expert axis."""

    def __init__(self, num_experts: int, d_model: int, d_hidden: int,
                 dtype: str = "float32", initializer_range: float = 0.02):
        super().__init__()
        self.num_experts = num_experts
        init = I.Normal(std=initializer_range)
        mk = lambda shape: self.create_parameter(shape, dtype=dtype,
                                                 default_initializer=init)
        self.w_gate = annotate(mk([num_experts, d_model, d_hidden]),
                               "expert", "embed", "expert_mlp")
        self.w_up = annotate(mk([num_experts, d_model, d_hidden]),
                             "expert", "embed", "expert_mlp")
        self.w_down = annotate(mk([num_experts, d_hidden, d_model]),
                               "expert", "expert_mlp", "embed")

    def forward(self, x):
        x = _raw(x)
        g = jnp.einsum("ecd,edm->ecm", x, self.w_gate._data)
        u = jnp.einsum("ecd,edm->ecm", x, self.w_up._data)
        h = constrain(jax.nn.silu(g) * u, "expert", None, "expert_mlp")
        out = jnp.einsum("ecm,emd->ecd", h, self.w_down._data)
        return constrain(out, "expert", None, "embed")

    def forward_ragged(self, x, group_sizes, expert_ids):
        """Dropless grouped swiglu (dispatch_mode="ragged"): megablox gmm
        kernel on TPU / lax.ragged_dot elsewhere — no capacity padding, no
        [E, C, d] staging in HBM."""
        from .....ops.grouped_matmul import grouped_dot

        x = _raw(x)
        g = grouped_dot(x, self.w_gate._data, group_sizes)
        u = grouped_dot(x, self.w_up._data, group_sizes)
        return grouped_dot(jax.nn.silu(g) * u, self.w_down._data,
                           group_sizes)

    def forward_pgmm(self, x_pad, tile_gids, tile_m=None, interpret=False):
        """Dropless grouped swiglu via the Pallas padded grouped matmul
        (dispatch_mode="pgmm", ops/grouped_matmul.py)."""
        from .....ops.grouped_matmul import TILE_M, pgmm

        tile_m = tile_m or TILE_M
        x_pad = _raw(x_pad)
        g = pgmm(x_pad, self.w_gate._data, tile_gids, tile_m, interpret)
        u = pgmm(x_pad, self.w_up._data, tile_gids, tile_m, interpret)
        return pgmm(jax.nn.silu(g) * u, self.w_down._data, tile_gids,
                    tile_m, interpret)


class MoELayer(Layer):
    """Mixture of Experts (reference moe_layer.py:263).

    Args:
        d_model: hidden size.
        num_experts: total number of experts (the reference's
            ``num_expert * world_size`` — one global count here; the ep mesh
            axis shards them).
        experts: optional stacked expert Layer (``[E, C, d] -> [E, C, d]``);
            default builds :class:`ExpertFFN` with ``d_hidden``.
        gate: "gshard" | "switch" | "naive" or a BaseGate instance.
        top_k: experts per token (gshard=2, switch=1).
        capacity_factor: per-expert capacity = ceil(tokens * k * cf / E).
    """

    def __init__(self, d_model: int, num_experts: int, d_hidden: Optional[int] = None,
                 experts: Optional[Layer] = None, gate: str = "gshard",
                 top_k: Optional[int] = None, capacity_factor: Optional[float] = None,
                 activation: str = "gelu", dtype: str = "float32",
                 recompute_interval: int = 0, group=None,
                 dispatch_mode: str = "auto"):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts
        # "einsum" (GShard dense — GSPMD lowers it to alltoall under ep
        # sharding), "scatter" (sparse O(n*k*d) dispatch), or "auto"
        # (scatter when E >= 16 OR the dense one-hot buffers would exceed
        # 16M elements — they are O(n^2 k) in tokens and OOM first; ep-mesh
        # users preferring the alltoall lowering at large n can force
        # dispatch_mode="einsum")
        self.dispatch_mode = dispatch_mode
        # capacity precedence: explicit arg > the gate's capacity (reference
        # GShardGate(capacity=...) API) > 1.25 default
        if capacity_factor is None and isinstance(gate, BaseGate):
            capacity_factor = getattr(gate, "capacity_factor", None)
        self.capacity_factor = 1.25 if capacity_factor is None else capacity_factor
        self.experts = experts if experts is not None else ExpertFFN(
            num_experts, d_model, d_hidden or 4 * d_model, activation, dtype)
        if isinstance(gate, BaseGate):
            self.gate = gate
            self.top_k = getattr(gate, "top_k", top_k or 2)
        elif gate == "gshard":
            self.top_k = top_k or 2
            self.gate = GShardGate(d_model, num_experts, topk=self.top_k)
        elif gate == "switch":
            self.top_k = 1
            self.gate = SwitchGate(d_model, num_experts)
        elif gate in ("naive", "topk"):
            self.top_k = top_k or 2
            self.gate = NaiveGate(d_model, num_experts, topk=self.top_k)
        else:
            raise ValueError(f"unknown gate {gate!r}")

    def capacity(self, num_tokens: int) -> int:
        cap = int(math.ceil(num_tokens * self.top_k * self.capacity_factor
                            / self.num_experts))
        return max(cap, self.top_k)

    def _routed_forward(self, x, *param_arrays):
        """The whole MoE computation as one pure fn (one taped op in eager)."""
        from .....jit.api import _Swap

        tensors = [t for _, t in self.named_parameters()]
        with _Swap(tensors, param_arrays):
            x = jnp.asarray(x)
            orig_shape = x.shape
            tokens = x.reshape(-1, orig_shape[-1])
            cap = self.capacity(tokens.shape[0])
            p = self.gate.probs(tokens)
            out, aux = routed_ffn(tokens, p, self.experts, self.top_k, cap,
                                  getattr(self.gate, "renormalize", True),
                                  dispatch_mode=self.dispatch_mode)
            if not getattr(self.gate, "use_aux", True):
                aux = jnp.zeros((), jnp.float32)
            out = out.reshape(orig_shape)
            if out.ndim == 3:
                out = constrain(out, "batch", "seq", "embed")
        return out, aux

    def forward(self, x):
        """x: [batch, seq, d_model] (or [tokens, d_model]). Returns the same
        kind as the input (Tensor in -> Tensor out, raw array in -> raw out)."""
        from .....core.op_registry import apply_fn

        was_tensor = isinstance(x, Tensor)
        tensors = [t for _, t in self.named_parameters()]
        out, aux = apply_fn("moe", self._routed_forward, x, *tensors)
        self.gate.set_loss(aux if was_tensor else _raw(aux))
        return out if was_tensor else _raw(out)

    def get_loss(self, clear=True):
        """The gate's aux (load-balance) loss for this forward."""
        return self.gate.get_loss(clear=clear)
