from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate, topk_dispatch  # noqa: F401
from .moe_layer import ExpertFFN, MoELayer, SwiGLUExpertFFN  # noqa: F401
