"""MoE gates — naive top-k, GShard top-2, Switch top-1.

Parity anchor: /root/reference/python/paddle/incubate/distributed/models/moe/gate/
(base_gate.py:25 BaseGate, naive_gate.py:28 NaiveGate, gshard_gate.py:31 GShardGate,
switch_gate.py:31 SwitchGate).

TPU-native: gates here return dense dispatch/combine tensors (GShard einsum
formulation) instead of the reference's index/position buffers — index_select/
scatter dispatch is a dynamic-shape pattern XLA can't tile; the dense one-hot
formulation keeps every shape static and lets GSPMD turn the dispatch einsum
into cross-device dispatch collectives over the ``ep`` mesh axis (this
XLA version picks all-reduce of per-expert partials — see docs/MOE_AB.md).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .....core.tensor import Tensor
from .....nn import initializer as I
from .....nn.layer.layers import Layer


def _raw(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


class BaseGate(Layer):
    """Reference base_gate.py:25 — holds the aux (load-balance) loss."""

    def __init__(self, num_expert, world_size=1):
        super().__init__()
        self.world_size = world_size
        self.num_expert = num_expert
        self.tot_expert = world_size * num_expert
        self.loss = None

    def forward(self, x):
        raise NotImplementedError("Base gate cannot be directly used for fwd")

    def set_loss(self, loss):
        self.loss = loss

    def get_loss(self, clear=True):
        loss = self.loss
        if clear:
            self.loss = None
        return loss


def _load_balance_loss(probs, first_choice_mask):
    """GShard aux loss: E * sum_e mean_tokens(prob_e) * mean_tokens(routed_e)."""
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(first_choice_mask.astype(probs.dtype), axis=0)
    return probs.shape[-1] * jnp.sum(me * ce)


def topk_routing(probs, k: int, capacity: int, renormalize: bool = True):
    """Sparse top-k routing with per-expert capacity — ONE source of truth
    for the GShard semantics (topk_dispatch assembles its dense one-hots
    from this, routed_ffn's scatter path consumes it directly).

    probs: [tokens, E]. Returns (expert_idx [n, k] int32, cap_pos [n, k]
    int32, weight [n, k], keep [n, k] bool, aux_loss). Tokens overflowing an
    expert's capacity get keep=False and weight 0 for that choice.
    """
    n, e = probs.shape
    remaining = probs
    prev_count = jnp.zeros((e,), jnp.int32)
    gate_sum = jnp.zeros((n,), probs.dtype)
    first_mask = None
    eidxs, cposs, gates, keeps = [], [], [], []
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)                    # [n]
        mask = jax.nn.one_hot(idx, e, dtype=probs.dtype)        # [n, e]
        if first_mask is None:
            first_mask = mask
        pos = jnp.cumsum(mask, axis=0) - 1 + prev_count[None, :].astype(probs.dtype)
        prev_count = prev_count + jnp.sum(mask, axis=0).astype(jnp.int32)
        within = (pos < capacity).astype(probs.dtype)
        mask = mask * within
        gate_j = jnp.sum(probs * mask, axis=-1)                 # [n]
        gate_sum = gate_sum + gate_j
        pos_tok = jnp.sum(pos * mask, axis=-1).astype(jnp.int32)  # [n]
        eidxs.append(idx.astype(jnp.int32))
        cposs.append(pos_tok)
        gates.append(gate_j)
        keeps.append(jnp.sum(mask, axis=-1) > 0)
        remaining = remaining * (1.0 - jax.nn.one_hot(idx, e, dtype=probs.dtype))
    w = jnp.stack(gates, axis=1)                                # [n, k]
    if renormalize and k > 1:
        w = w / jnp.maximum(gate_sum, 1e-9)[:, None]
    aux = _load_balance_loss(probs, first_mask)
    return (jnp.stack(eidxs, axis=1), jnp.stack(cposs, axis=1), w,
            jnp.stack(keeps, axis=1), aux)


def topk_dispatch(probs, k: int, capacity: int, renormalize: bool = True):
    """Dense top-k routing with per-expert capacity.

    probs: [tokens, E] softmax gate probabilities.
    Returns (combine [tokens, E, C], dispatch_mask [tokens, E, C] bool, aux_loss).
    Tokens overflowing an expert's capacity are dropped for that choice
    (GShard semantics). Dense assembly over :func:`topk_routing`.
    """
    n, e = probs.shape
    eidx, cpos, w, keep, aux = topk_routing(probs, k, capacity, renormalize)
    onehot_e = jax.nn.one_hot(eidx, e, dtype=probs.dtype)       # [n, k, E]
    onehot_c = jax.nn.one_hot(cpos, capacity, dtype=probs.dtype)  # [n, k, C]
    wk = w * keep.astype(probs.dtype)
    combine = jnp.einsum("nk,nke,nkc->nec", wk, onehot_e, onehot_c)
    dispatch = combine > 0
    return combine, dispatch, aux


class NaiveGate(BaseGate):
    """Reference naive_gate.py:28 — linear scorer + top-k, no aux loss."""

    renormalize = True   # renormalize combine weights over the selected top-k
    use_aux = False      # whether the load-balance aux loss trains the gate

    def __init__(self, d_model, num_expert, world_size=1, topk=2):
        super().__init__(num_expert, world_size)
        self.top_k = topk
        self.gate_weight = self.create_parameter(
            [d_model, self.tot_expert], dtype="float32",
            default_initializer=I.XavierUniform())

    def probs(self, inp):
        logits = jnp.matmul(_raw(inp).astype(jnp.float32), self.gate_weight._data)
        return jax.nn.softmax(logits, axis=-1)

    scores = probs

    def forward(self, inp, capacity: int):
        p = self.probs(inp)
        combine, dispatch, aux = topk_dispatch(p, self.top_k, capacity,
                                               self.renormalize)
        self.set_loss(aux if self.use_aux else jnp.zeros((), jnp.float32))
        return combine, dispatch


class GShardGate(NaiveGate):
    """Reference gshard_gate.py:31 — top-2 with capacity + load-balance aux loss."""

    use_aux = True

    def __init__(self, d_model, num_expert, world_size=1, topk=2,
                 capacity=(1.2, 2.4), random_routing=True, group=None):
        if topk != 2:
            raise ValueError("topk should be 2 in gshard")
        super().__init__(d_model, num_expert, world_size, topk=2)
        self.capacity_factor = capacity[0] if isinstance(capacity, (tuple, list)) else capacity


class SwitchGate(NaiveGate):
    """Reference switch_gate.py:31 — top-1 with capacity + aux loss."""

    renormalize = False
    use_aux = True

    def __init__(self, d_model, num_expert, world_size=1, topk=1,
                 switch_eps=0.1, capacity=(1.2, 2.4), group=None):
        if topk != 1:
            raise ValueError("topk should be 1 in switch")
        super().__init__(d_model, num_expert, world_size, topk=1)
        self.switch_eps = switch_eps

    def probs(self, inp):
        x = _raw(inp).astype(jnp.float32)
        logits = jnp.matmul(x, self.gate_weight._data)
        if self.training and self.switch_eps > 0:
            # reference switch_gate.py: multiplicative jitter noise in training
            from .....framework.random import next_key

            noise = jax.random.uniform(
                next_key(), logits.shape, jnp.float32,
                1.0 - self.switch_eps, 1.0 + self.switch_eps)
            logits = logits * noise
        return jax.nn.softmax(logits, axis=-1)
