"""Declarative op registry + eager dispatcher.

Replaces the reference's five-codegen YAML pipeline (phi/ops/yaml/ops.yaml + api_gen.py
+ eager_gen.py + op_gen.py + python_c_gen.py) with ONE runtime registry: each op is a
pure jax-traceable function plus metadata (AMP behavior, optional SPMD rule). The
dispatcher is the analogue of the generated ``*_ad_func`` pattern
(fluid/eager/api/manual/eager_manual/forwards/add_n_fwd_func.cc:25):
  profile scope -> AMP autocast -> [tape record via jax.vjp] -> kernel (jnp/lax/pallas)
  -> nan/inf check -> wrap outputs.
Under a jax trace (jit/grad/vmap/shard_map) the tape is bypassed and the pure fn is
inlined into the surrounding jaxpr — eager and compiled modes share one implementation.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from .. import flags
from . import autograd_engine, static_graph
from .tensor import Tensor

# AMP categories (reference: python/paddle/amp/amp_lists.py)
AMP_WHITE = "white"  # run in low precision (matmul/conv class)
AMP_BLACK = "black"  # keep fp32 (softmax/norm/exp class)
AMP_NEUTRAL = "neutral"  # follow inputs


class OpDef:
    __slots__ = ("name", "fn", "amp", "spmd_rule", "n_outputs", "doc")

    def __init__(self, name, fn, amp=AMP_NEUTRAL, spmd_rule=None, doc=""):
        self.name = name
        self.fn = fn
        self.amp = amp
        self.spmd_rule = spmd_rule
        self.doc = doc


OPS: Dict[str, OpDef] = {}


def register_op(name: str, amp: str = AMP_NEUTRAL, spmd_rule=None):
    """Decorator: register a pure jax function as a framework op."""

    def deco(fn):
        OPS[name] = OpDef(name, fn, amp=amp, spmd_rule=spmd_rule, doc=fn.__doc__ or "")
        return fn

    return deco


def get_op(name: str) -> OpDef:
    return OPS[name]


# ---- AMP hook (set by paddle_tpu.amp to avoid circular import) ----
amp_state = None  # type: Optional[Any]

# ---- profiler hook (set by paddle_tpu.profiler) ----
profile_scope = None  # type: Optional[Callable]

# ---- tensor-stats dump hook (set by paddle_tpu.amp.debugging) ----
stats_recorder = None  # type: Optional[Any]


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _has_tracer(arrays) -> bool:
    return any(_is_tracer(a) for a in arrays)


def _amp_cast_preserving_graph(a: Tensor, tgt):
    """Cast a tensor for AMP while keeping its autograd linkage."""
    return apply_fn("cast", lambda x: x.astype(tgt), a)


def _check_nan_inf(name, arrays):
    num_nan = num_inf = 0
    for a in arrays:
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating):
            if not bool(jnp.isfinite(a).all()):
                num_nan += int(jnp.isnan(a).sum())
                num_inf += int(jnp.isinf(a).sum())
    if num_nan or num_inf:
        # report into the shared numeric health word (PT-NUM-001/002) so
        # eager detections land beside the jitted guard's and AMP's
        from ..framework import numeric_guard

        numeric_guard.report_nan_inf(num_nan, num_inf, source=f"op:{name}")
        msg = (f"Operator {name} output contains {num_nan} nan / "
               f"{num_inf} inf values")
        if flags.get_flag("check_nan_inf_level") == 0:
            raise FloatingPointError(msg)
        import warnings

        warnings.warn(msg)


def apply(name: str, *args, **kwargs):
    """Dispatch a registered op over Tensor/array args."""
    return apply_fn(name, OPS[name].fn, *args, _opdef=OPS[name], **kwargs)


def apply_fn(name: str, fn: Callable, *args, _opdef: Optional[OpDef] = None, **kwargs):
    """Dispatch an (unregistered) pure function as an op — same tape/AMP semantics.

    Positional args may be Tensors (differentiable leaves), arrays, or static values.
    kwargs are always static.
    """
    op = _opdef or OpDef(name, fn)

    if amp_state is not None and amp_state.enabled and op.amp != AMP_NEUTRAL:
        cat = amp_state.classify(op.name, op.amp)
        if cat == AMP_WHITE:
            tgt = amp_state.dtype
            args = tuple(
                _amp_cast_preserving_graph(a, tgt)
                if isinstance(a, Tensor) and a.dtype == jnp.float32
                else a
                for a in args
            )
            if flags.get_flag("low_precision_op_list"):
                amp_state.record_op(op.name)
        elif cat == AMP_BLACK:
            args = tuple(
                _amp_cast_preserving_graph(a, jnp.float32)
                if isinstance(a, Tensor) and a.dtype in (jnp.bfloat16, jnp.float16)
                else a
                for a in args
            )

    # static-graph interception: any symbolic Variable input routes the call
    # into the current Program as a recorded Operation; creation ops (no tensor
    # inputs) also record while a program_guard is open in static mode, so
    # feed-independent subgraphs exist in the IR for constant folding
    # (core/static_graph.py)
    if any(isinstance(a, static_graph.Variable) for a in args) or (
        static_graph.recording_constants()
        and not any(isinstance(a, Tensor) for a in args)
    ):
        return static_graph.record_op(name, fn, args, kwargs)

    tensor_idx = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    arrays = [args[i]._data for i in tensor_idx]
    tracing = _has_tracer(arrays)

    record = (
        not tracing
        and autograd_engine.grad_enabled()
        and any(not args[i].stop_gradient for i in tensor_idx)
    )

    def call_with(arrs):
        full = list(args)
        for i, a in zip(tensor_idx, arrs):
            full[i] = a
        return fn(*full, **kwargs)

    if record:
        diff_idx = [
            i
            for i in tensor_idx
            if jnp.issubdtype(args[i].dtype, jnp.floating)
            or jnp.issubdtype(args[i].dtype, jnp.complexfloating)
        ]
        diff_arrays = [args[i]._data for i in diff_idx]

        # SNAPSHOT non-diff tensor inputs now: the deferred backward (and
        # create_graph's _taped_vjp) replays `pure` later, and an in-place
        # mutation of an index/mask Tensor in between must not change what
        # the recorded op saw (Tensor._data rebinds on set_value/copy_)
        nondiff_snap = {i: args[i]._data for i in tensor_idx
                        if i not in diff_idx}

        def pure(*darrs):
            full = list(args)
            it = iter(darrs)
            for i in tensor_idx:
                full[i] = next(it) if i in diff_idx else nondiff_snap[i]
            return fn(*full, **kwargs)

        # DEFERRED linearization: run the plain forward now (one XLA
        # dispatch); backward() traces the vjp lazily from (pure, primals).
        # Measured 25x lower per-op tape overhead (benchmarks/
        # eager_dispatch.py) vs calling jax.vjp here, and ops never
        # differentiated never pay for a linearize at all.
        from ..framework import random as _frandom

        _rng_key0 = _frandom._global["key"]
        _rng_stack = _frandom._ctx_stack()
        _rng_cnt0 = _rng_stack[-1]["count"] if _rng_stack else None
        out = pure(*diff_arrays)
        if (_frandom._global["key"] is not _rng_key0
                or (_rng_stack and _rng_stack[-1]["count"] != _rng_cnt0)):
            # the op drew RNG inside (dropout etc.): a deferred re-run would
            # sample a DIFFERENT mask than the forward output used. Rewind
            # the stream and linearize NOW — jax.vjp replays the same keys,
            # so output, residuals, and the net stream advance all match.
            _frandom._global["key"] = _rng_key0
            if _rng_stack:
                _rng_stack[-1]["count"] = _rng_cnt0
            out, vjp_fn = jax.vjp(pure, *diff_arrays)
            primals = None
        else:
            vjp_fn = None
            primals = diff_arrays
        out_list, single = (list(out), False) if isinstance(out, (tuple, list)) else ([out], True)
        node = autograd_engine.GradNode(
            name,
            vjp_fn,
            [args[i] for i in diff_idx],
            [(o.shape, o.dtype) for o in out_list],
            pure_fn=pure,
            primals=primals,
        )
        results = []
        for idx, o in enumerate(out_list):
            t = Tensor(o, stop_gradient=False)
            t._node = node
            t._out_idx = idx
            results.append(t)
        if flags.get_flag("check_nan_inf"):
            _check_nan_inf(name, out_list)
        if stats_recorder is not None:
            stats_recorder.record(name, out_list)
        return results[0] if single else tuple(results)

    out = call_with(arrays)
    if not tracing:
        outs = out if isinstance(out, (tuple, list)) else [out]
        if flags.get_flag("check_nan_inf"):
            _check_nan_inf(name, [o for o in outs if hasattr(o, "dtype")])
        if stats_recorder is not None:
            stats_recorder.record(name, [o for o in outs if hasattr(o, "dtype")])
    if isinstance(out, (tuple, list)):
        return tuple(Tensor(o) if not isinstance(o, Tensor) else o for o in out)
    return Tensor(out) if not isinstance(out, Tensor) else out
