"""The framework Tensor: a thin, mutable handle over an immutable ``jax.Array``.

Design (vs reference): the reference's ``paddle::Tensor`` (phi/api/include/tensor.h:82)
owns a DenseTensor + AutogradMeta. Here the payload is a ``jax.Array`` (XLA owns
memory/placement); autograd metadata is a pointer into the eager tape
(`paddle_tpu.core.autograd_engine`). Tensor is registered as a JAX pytree so it can
flow through ``jax.jit`` / ``jax.grad`` / shardings transparently.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtype_mod

_tensor_counter = [0]


class Tensor:
    __slots__ = (
        "_data",
        "stop_gradient",
        "_grad",
        "_node",
        "_out_idx",
        "name",
        "persistable",
        "_hooks",
        "is_parameter",
        "__weakref__",
        "__dict__",  # escape hatch: dist attrs (process_mesh/placements), pending buffer updates
    )

    def __init__(self, data, dtype=None, stop_gradient: bool = True, name: Optional[str] = None):
        if isinstance(data, Tensor):
            data = data._data
        if dtype is not None:
            dtype = dtype_mod.convert_dtype(dtype)
        if isinstance(data, (jax.Array, jax.core.Tracer)):
            self._data = data.astype(dtype) if (dtype is not None and data.dtype != dtype) else data
        else:
            if dtype is None and isinstance(data, (float,)):
                dtype = dtype_mod.get_default_dtype()
            if dtype is None and isinstance(data, np.ndarray) and data.dtype == np.float64:
                dtype = dtype_mod.get_default_dtype()
            self._data = jnp.asarray(data, dtype=dtype)
        self.stop_gradient = stop_gradient
        self._grad: Optional[Tensor] = None
        self._node = None
        self._out_idx = 0
        if name is None:
            _tensor_counter[0] += 1
            name = f"generated_tensor_{_tensor_counter[0]}"
        self.name = name
        self.persistable = False
        self._hooks = None
        self.is_parameter = False

    # ---- basic properties ----
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def dim(self):
        return self._data.ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def place(self):
        try:
            devs = self._data.devices()
            return next(iter(devs))
        except Exception:
            return None

    @property
    def grad(self) -> Optional["Tensor"]:
        return self._grad

    @grad.setter
    def grad(self, value):
        if value is not None and not isinstance(value, Tensor):
            value = Tensor(value)
        self._grad = value

    @property
    def is_leaf(self) -> bool:
        return self._node is None

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    # ---- conversion ----
    def numpy(self) -> np.ndarray:
        return np.asarray(self._data)

    def item(self):
        return self._data.item()

    def tolist(self):
        return np.asarray(self._data).tolist()

    def __array__(self, dtype=None):
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a

    def __jax_array__(self):
        return self._data

    def __float__(self):
        return float(self._data)

    def __int__(self):
        return int(self._data)

    def __bool__(self):
        return bool(self._data)

    def __index__(self):
        return int(self._data)

    # ---- autograd ----
    def backward(self, grad_tensor: Optional["Tensor"] = None, retain_graph: bool = False):
        from . import autograd_engine

        autograd_engine.run_backward(self, grad_tensor, retain_graph)

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    def detach(self) -> "Tensor":
        t = Tensor(self._data, stop_gradient=True, name=self.name + ".detach")
        return t

    def detach_(self) -> "Tensor":
        self._node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from . import op_registry

        return op_registry.apply_fn("clone", lambda x: x + 0, self)

    def register_hook(self, hook):
        if self._node is not None:
            # non-leaf: hook fires on this tensor's cotangent during backward
            if self._node.hooks is None:
                self._node.hooks = {}
            self._node.hooks.setdefault(self._out_idx, []).append(hook)
            hooks_ref = self._node.hooks[self._out_idx]

            class _NodeHandle:
                def remove(h):
                    if hook in hooks_ref:
                        hooks_ref.remove(hook)

            return _NodeHandle()
        if self._hooks is None:
            self._hooks = []
        self._hooks.append(hook)

        class _Handle:
            def __init__(h, hooks, fn):
                h._hooks, h._fn = hooks, fn

            def remove(h):
                if h._fn in h._hooks:
                    h._hooks.remove(h._fn)

        return _Handle(self._hooks, hook)

    # ---- mutation (eager only) ----
    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._data
        arr = jnp.asarray(value, dtype=self._data.dtype)
        if tuple(arr.shape) != tuple(self._data.shape):
            raise ValueError(f"set_value shape mismatch: {arr.shape} vs {self._data.shape}")
        self._data = arr
        return self

    def copy_(self, other, *a, **k):
        return self.set_value(other)

    def _replace_(self, new_data, node=None, idx=0):
        """Internal: rebind payload (used by in-place ops and functional swap)."""
        self._data = new_data
        self._node = node
        self._out_idx = idx
        return self

    def __repr__(self):
        grad_info = "" if self.stop_gradient else ", stop_gradient=False"
        return (
            f"Tensor(shape={self.shape}, dtype={dtype_mod.dtype_name(self.dtype)}{grad_info},\n"
            f"       {np.asarray(self._data)!r})"
        )

    __str__ = __repr__

    def __hash__(self):
        return id(self)

    # value/pin/cuda parity helpers
    def pin_memory(self):
        return self

    def cuda(self, *a, **k):
        return self

    def cpu(self):
        return self

    def to(self, *args, **kwargs):
        dtype = kwargs.get("dtype")
        for a in args:
            if isinstance(a, str) and a in ("cpu", "gpu", "tpu") or hasattr(a, "platform"):
                continue
            dtype = a
        if dtype is not None:
            return self.astype(dtype)
        return self

    def astype(self, dtype):  # overridden by tensor method installation (graph-aware)
        from . import op_registry

        dtype = dtype_mod.convert_dtype(dtype)
        return op_registry.apply_fn("cast", lambda x: x.astype(dtype), self)

    def value(self):
        return self

    def get_tensor(self):
        return self


def _tensor_flatten(t: Tensor):
    # NOTE: aux must NOT contain per-instance strings (e.g. .name) — jit caches on
    # pytree aux equality and unique names would force a retrace per call.
    return (t._data,), (t.stop_gradient,)


def _tensor_unflatten(aux, children):
    (data,) = children
    t = Tensor.__new__(Tensor)
    t._data = data
    t.stop_gradient = aux[0]
    t._grad = None
    t._node = None
    t._out_idx = 0
    t.name = "unflattened_tensor"
    t.persistable = False
    t._hooks = None
    t.is_parameter = False
    return t


jax.tree_util.register_pytree_node(Tensor, _tensor_flatten, _tensor_unflatten)


class Parameter(Tensor):
    """Trainable tensor (reference: python/paddle/base/framework.py EagerParamBase)."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip", "initialized")

    def __init__(self, data, dtype=None, name=None, trainable=True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.is_parameter = True
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.initialized = True


jax.tree_util.register_pytree_node(
    Parameter,
    _tensor_flatten,
    lambda aux, ch: _tensor_unflatten(aux, ch),
)


def unwrap(x):
    """Tensor | array | scalar -> jax-compatible value."""
    return x._data if isinstance(x, Tensor) else x


def wrap(x, stop_gradient=True) -> Tensor:
    return x if isinstance(x, Tensor) else Tensor(x, stop_gradient=stop_gradient)
