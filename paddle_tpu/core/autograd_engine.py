"""Eager autograd: tape of GradNodes + queue-based backward.

TPU-native redesign of the reference's eager engine (fluid/eager/grad_node_info.h:197
``GradNodeBase``, fluid/eager/backward.cc:105 ``RunBackward``): instead of per-op
hand/generated C++ grad kernels, every recorded op stores the ``jax.vjp`` closure of
its (pure, jax-traceable) forward fn. Backward is a reverse-topological walk that
feeds cotangents through those closures — each closure itself runs on-device via XLA.
"""

from __future__ import annotations

import threading
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .tensor import Tensor

_state = threading.local()


def grad_enabled() -> bool:
    return getattr(_state, "grad_enabled", True)


def set_grad_enabled(mode: bool) -> bool:
    prev = grad_enabled()
    _state.grad_enabled = mode
    return prev


class no_grad:
    """Context manager / decorator disabling tape recording (paddle.no_grad)."""

    def __enter__(self):
        self._prev = set_grad_enabled(False)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with no_grad():
                return fn(*args, **kwargs)

        return wrapper


class enable_grad:
    def __enter__(self):
        self._prev = set_grad_enabled(True)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False


class GradNode:
    """One recorded op: maps output cotangents -> input cotangents via stored vjp."""

    __slots__ = ("name", "vjp_fn", "inputs", "out_avals", "n_outputs", "hooks")

    def __init__(self, name: str, vjp_fn, inputs: List[Tensor], out_avals):
        self.name = name
        self.vjp_fn = vjp_fn
        self.inputs = inputs  # differentiable input Tensors, in vjp order
        self.out_avals = out_avals  # [(shape, dtype)] per output
        self.n_outputs = len(out_avals)
        self.hooks = None  # {out_idx: [fn]}

    def __repr__(self):
        return f"GradNode<{self.name}>"


def _zero_cotangent(shape, dtype):
    if jnp.issubdtype(dtype, jnp.floating) or jnp.issubdtype(dtype, jnp.complexfloating):
        return jnp.zeros(shape, dtype)
    return np.zeros(shape, jax.dtypes.float0)


def _accumulate(slot, value):
    return value if slot is None else slot + value


def run_backward(root: Tensor, grad_tensor: Optional[Tensor] = None, retain_graph: bool = False,
                 sink=None, capture_tensors=None):
    """Reverse-topological cotangent propagation (cf. backward.cc:105).

    When ``sink`` is given (paddle.grad mode), cotangents for ``capture_tensors``
    are collected into ``sink[id(tensor)]`` and NO ``.grad`` fields are touched —
    gradients-without-side-effects, matching the reference's ``paddle.grad``.
    """
    if grad_tensor is None:
        if not jnp.issubdtype(root.dtype, jnp.floating):
            raise RuntimeError("backward() root must be floating point")
        seed = jnp.ones(root._data.shape, root.dtype)
    else:
        seed = grad_tensor._data if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)

    # map (node id, out idx) -> sink key for non-leaf capture; id(tensor) for leaves
    cap_nonleaf = {}
    cap_leaf = set()
    if sink is not None:
        for t in capture_tensors or ():
            if t._node is not None:
                cap_nonleaf[(id(t._node), t._out_idx)] = id(t)
            else:
                cap_leaf.add(id(t))

    if root._node is None:
        if sink is not None:
            if id(root) in cap_leaf:
                sink[id(root)] = _accumulate(sink.get(id(root)), seed)
        elif not root.stop_gradient:
            _write_leaf_grad(root, seed)
        return

    # topo order over nodes (iterative DFS)
    order: List[GradNode] = []
    visited = set()
    stack = [(root._node, False)]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in visited:
            continue
        visited.add(id(node))
        stack.append((node, True))
        for t in node.inputs:
            if t._node is not None and id(t._node) not in visited:
                stack.append((t._node, False))

    # cotangent accumulation buffers, keyed by node id
    pending = {id(n): [None] * n.n_outputs for n in order}
    pending[id(root._node)][root._out_idx] = _accumulate(
        pending[id(root._node)][root._out_idx], seed
    )

    for node in reversed(order):
        cots = pending.pop(id(node))
        if cap_nonleaf:
            for idx, c in enumerate(cots):
                key = cap_nonleaf.get((id(node), idx))
                if key is not None and c is not None:
                    sink[key] = _accumulate(sink.get(key), c)
        if all(c is None for c in cots):
            continue
        full = tuple(
            c if c is not None else _zero_cotangent(shape, dt)
            for c, (shape, dt) in zip(cots, node.out_avals)
        )
        if node.hooks:
            full = list(full)
            for idx, fns in node.hooks.items():
                for fn in fns:
                    out = fn(Tensor(full[idx]))
                    if out is not None:
                        full[idx] = out._data if isinstance(out, Tensor) else out
            full = tuple(full)
        if node.vjp_fn is None:
            raise RuntimeError(
                "Trying to backward through the graph a second time "
                "(use retain_graph=True)."
            )
        payload = full[0] if node.n_outputs == 1 else full
        in_cots = node.vjp_fn(payload)
        if not retain_graph:
            node.vjp_fn = None
        for t, g in zip(node.inputs, in_cots):
            if g is None or (hasattr(g, "dtype") and g.dtype == jax.dtypes.float0):
                continue
            if t._node is not None:
                buf = pending.get(id(t._node))
                if buf is not None:
                    buf[t._out_idx] = _accumulate(buf[t._out_idx], g)
            elif sink is not None:
                if id(t) in cap_leaf:
                    sink[id(t)] = _accumulate(sink.get(id(t)), g)
            elif not t.stop_gradient:
                _write_leaf_grad(t, g)


def _write_leaf_grad(t: Tensor, g):
    if t._hooks:
        for fn in t._hooks:
            out = fn(Tensor(g))
            if out is not None:
                g = out._data if isinstance(out, Tensor) else out
    if t._grad is None:
        gt = Tensor(g)
        gt.stop_gradient = True
        t._grad = gt
    else:
        t._grad._data = t._grad._data + g


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph=None,
    create_graph=False,
    only_inputs=True,
    allow_unused=False,
    no_grad_vars=None,
):
    """paddle.grad analogue: compute grads of outputs wrt inputs WITHOUT touching any
    tensor's ``.grad`` (side-effect-free, incl. unrelated model parameters).
    Works for both leaf and intermediate (non-leaf) inputs."""
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is not None and not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]

    sink = {}
    retain = True if retain_graph is None else retain_graph
    for i, out in enumerate(outputs):
        g = grad_outputs[i] if grad_outputs is not None else None
        run_backward(out, g, retain_graph=retain, sink=sink, capture_tensors=inputs)
    results = []
    for t in inputs:
        g = sink.get(id(t))
        if g is None and not allow_unused:
            raise RuntimeError(f"Tensor {t.name} is unused in the graph")
        results.append(Tensor(g) if g is not None else None)
    return results
