"""Eager autograd: tape of GradNodes + queue-based backward.

TPU-native redesign of the reference's eager engine (fluid/eager/grad_node_info.h:197
``GradNodeBase``, fluid/eager/backward.cc:105 ``RunBackward``): instead of per-op
hand/generated C++ grad kernels, every recorded op stores the ``jax.vjp`` closure of
its (pure, jax-traceable) forward fn. Backward is a reverse-topological walk that
feeds cotangents through those closures — each closure itself runs on-device via XLA.
"""

from __future__ import annotations

import threading
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .tensor import Tensor

_state = threading.local()


def grad_enabled() -> bool:
    return getattr(_state, "grad_enabled", True)


def set_grad_enabled(mode: bool) -> bool:
    prev = grad_enabled()
    _state.grad_enabled = mode
    return prev


class no_grad:
    """Context manager / decorator disabling tape recording (paddle.no_grad)."""

    def __enter__(self):
        self._prev = set_grad_enabled(False)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with no_grad():
                return fn(*args, **kwargs)

        return wrapper


class enable_grad:
    def __enter__(self):
        self._prev = set_grad_enabled(True)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False


class GradNode:
    """One recorded op: maps output cotangents -> input cotangents via stored vjp."""

    __slots__ = ("name", "vjp_fn", "inputs", "out_avals", "n_outputs", "hooks",
                 "pure_fn", "primals")

    def __init__(self, name: str, vjp_fn, inputs: List[Tensor], out_avals,
                 pure_fn=None, primals=None):
        self.name = name
        self.vjp_fn = vjp_fn
        self.inputs = inputs  # differentiable input Tensors, in vjp order
        self.out_avals = out_avals  # [(shape, dtype)] per output
        self.n_outputs = len(out_avals)
        self.hooks = None  # {out_idx: [fn]}
        # pure forward fn over the diff-input arrays — enables create_graph=True
        # (double backward): the VJP is re-derived and DISPATCHED as a taped op
        self.pure_fn = pure_fn
        # DEFERRED linearization (the eager fast path): the dispatcher stores
        # the diff-input arrays instead of calling jax.vjp per op — recording
        # then costs one XLA dispatch (~26us) instead of a full linearize
        # trace (~1.3ms, measured benchmarks/eager_dispatch.py); backward()
        # derives the vjp lazily from (pure_fn, primals).
        self.primals = primals

    def __repr__(self):
        return f"GradNode<{self.name}>"


def _zero_cotangent(shape, dtype):
    if jnp.issubdtype(dtype, jnp.floating) or jnp.issubdtype(dtype, jnp.complexfloating):
        return jnp.zeros(shape, dtype)
    return np.zeros(shape, jax.dtypes.float0)


def _accumulate(slot, value):
    if slot is None:
        return value
    if isinstance(slot, Tensor) or isinstance(value, Tensor):
        a = slot if isinstance(slot, Tensor) else Tensor(slot)
        return a + value  # dispatched add — keeps create_graph linkage
    return slot + value


def _taped_vjp(node: "GradNode", cots):
    """create_graph=True: run this node's VJP as a *dispatched op* so the tape
    records it and the returned cotangents are themselves differentiable
    (reference: double-grad nodes created by RunBackward,
    fluid/eager/backward.cc:105)."""
    from .op_registry import apply_fn

    if node.pure_fn is None:
        if node.vjp_fn is None:
            # a prior non-retained backward consumed and freed this node
            raise RuntimeError(
                "Trying to backward through the graph a second time "
                "(use retain_graph=True).")
        raise RuntimeError(
            f"create_graph=True cannot differentiate through '{node.name}' — "
            "this node has no recorded pure forward (PyLayer / to_static). "
            "Use paddle.autograd.jacobian/hessian, or express the op through "
            "the dispatcher.")
    n_out = node.n_outputs

    def grad_fn(*flat):
        cot_arrays, primal_arrays = flat[:n_out], flat[n_out:]
        _, vjp = jax.vjp(node.pure_fn, *primal_arrays)
        payload = cot_arrays[0] if n_out == 1 else tuple(cot_arrays)
        res = vjp(payload)
        # single-input nodes return the bare array so the dispatcher's
        # single-output payload convention holds at the next grad level
        return res[0] if len(res) == 1 else tuple(res)

    args = [c if isinstance(c, Tensor) else Tensor(c) for c in cots]
    args += list(node.inputs)
    out = apply_fn(node.name + "_grad", grad_fn, *args)
    return out if isinstance(out, tuple) else (out,)


def run_backward(root: Tensor, grad_tensor: Optional[Tensor] = None, retain_graph: bool = False,
                 sink=None, capture_tensors=None, create_graph: bool = False):
    """Reverse-topological cotangent propagation (cf. backward.cc:105).

    When ``sink`` is given (paddle.grad mode), cotangents for ``capture_tensors``
    are collected into ``sink[id(tensor)]`` and NO ``.grad`` fields are touched —
    gradients-without-side-effects, matching the reference's ``paddle.grad``.
    """
    if grad_tensor is None:
        if not jnp.issubdtype(root.dtype, jnp.floating):
            raise RuntimeError("backward() root must be floating point")
        seed = jnp.ones(root._data.shape, root.dtype)
    elif create_graph and isinstance(grad_tensor, Tensor):
        seed = grad_tensor  # keep linkage: the seed may itself require grad
    else:
        seed = grad_tensor._data if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)

    # map (node id, out idx) -> sink key for non-leaf capture; id(tensor) for leaves
    cap_nonleaf = {}
    cap_leaf = set()
    if sink is not None:
        for t in capture_tensors or ():
            if t._node is not None:
                cap_nonleaf[(id(t._node), t._out_idx)] = id(t)
            else:
                cap_leaf.add(id(t))

    if root._node is None:
        if sink is not None:
            if id(root) in cap_leaf:
                sink[id(root)] = _accumulate(sink.get(id(root)), seed)
        elif not root.stop_gradient:
            _write_leaf_grad(root, seed)
        return

    # topo order over nodes (iterative DFS)
    order: List[GradNode] = []
    visited = set()
    stack = [(root._node, False)]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in visited:
            continue
        visited.add(id(node))
        stack.append((node, True))
        for t in node.inputs:
            if t._node is not None and id(t._node) not in visited:
                stack.append((t._node, False))

    # cotangent accumulation buffers, keyed by node id
    pending = {id(n): [None] * n.n_outputs for n in order}
    pending[id(root._node)][root._out_idx] = _accumulate(
        pending[id(root._node)][root._out_idx], seed
    )

    for node in reversed(order):
        cots = pending.pop(id(node))
        if cap_nonleaf:
            for idx, c in enumerate(cots):
                key = cap_nonleaf.get((id(node), idx))
                if key is not None and c is not None:
                    sink[key] = _accumulate(sink.get(key), c)
        if all(c is None for c in cots):
            continue
        full = tuple(
            c if c is not None else _zero_cotangent(shape, dt)
            for c, (shape, dt) in zip(cots, node.out_avals)
        )
        if node.hooks:
            full = list(full)
            for idx, fns in node.hooks.items():
                for fn in fns:
                    c = full[idx]
                    out = fn(c if isinstance(c, Tensor) else Tensor(c))
                    if out is not None:
                        full[idx] = out
            full = tuple(full)
        if create_graph:
            in_cots = _taped_vjp(node, full)
        else:
            vjp_fn = node.vjp_fn
            if vjp_fn is None:
                if node.pure_fn is None or node.primals is None:
                    raise RuntimeError(
                        "Trying to backward through the graph a second time "
                        "(use retain_graph=True)."
                    )
                # deferred linearization: trace the op's vjp now (recording
                # stored only the primal arrays — see GradNode.primals)
                _, vjp_fn = jax.vjp(node.pure_fn, *node.primals)
                if retain_graph:
                    # later backwards reuse the trace instead of re-deriving
                    node.vjp_fn = vjp_fn
            full = tuple(c._data if isinstance(c, Tensor) else c for c in full)
            payload = full[0] if node.n_outputs == 1 else full
            in_cots = vjp_fn(payload)
            if not retain_graph:
                node.vjp_fn = None
                node.pure_fn = None  # frees the forward-args closure too
                node.primals = None
        for t, g in zip(node.inputs, in_cots):
            if g is None or (hasattr(g, "dtype") and g.dtype == jax.dtypes.float0):
                continue
            if t._node is not None:
                buf = pending.get(id(t._node))
                if buf is not None:
                    buf[t._out_idx] = _accumulate(buf[t._out_idx], g)
            elif sink is not None:
                if id(t) in cap_leaf:
                    sink[id(t)] = _accumulate(sink.get(id(t)), g)
            elif not t.stop_gradient:
                _write_leaf_grad(t, g)


def _write_leaf_grad(t: Tensor, g):
    if t._hooks:
        for fn in t._hooks:
            out = fn(g if isinstance(g, Tensor) else Tensor(g))
            if out is not None:
                g = out._data if isinstance(out, Tensor) else out
    if isinstance(g, Tensor):  # create_graph: .grad stays part of the graph
        if t._grad is None:
            t._grad = g
        else:
            t._grad = t._grad + g
        return
    if t._grad is None:
        gt = Tensor(g)
        gt.stop_gradient = True
        t._grad = gt
    else:
        t._grad._data = t._grad._data + g


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph=None,
    create_graph=False,
    only_inputs=True,
    allow_unused=False,
    no_grad_vars=None,
):
    """paddle.grad analogue: compute grads of outputs wrt inputs WITHOUT touching any
    tensor's ``.grad`` (side-effect-free, incl. unrelated model parameters).
    Works for both leaf and intermediate (non-leaf) inputs."""
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is not None and not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]

    sink = {}
    retain = True if retain_graph is None else retain_graph
    for i, out in enumerate(outputs):
        g = grad_outputs[i] if grad_outputs is not None else None
        run_backward(out, g, retain_graph=retain, sink=sink, capture_tensors=inputs,
                     create_graph=create_graph)
    results = []
    for t in inputs:
        g = sink.get(id(t))
        if g is None and not allow_unused:
            raise RuntimeError(f"Tensor {t.name} is unused in the graph")
        if g is None:
            results.append(None)
        else:
            results.append(g if isinstance(g, Tensor) else Tensor(g))
    return results
