"""Dtype system.

Mirrors the reference's dtype surface (paddle/phi/common/data_type.h, exposed as
``paddle.float32`` etc.) on top of JAX dtypes. TPU-first: bfloat16 is a first-class
dtype; float64 works only when x64 is enabled (off by default, as on TPU).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_STR2DTYPE = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "float32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
    "fp16": float16,
    "bf16": bfloat16,
    "fp32": float32,
    "fp64": float64,
}


def convert_dtype(dtype):
    """Normalize a user-supplied dtype (str / numpy / jax) to a jnp dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _STR2DTYPE:
            raise ValueError(f"Unknown dtype string: {dtype!r}")
        return _STR2DTYPE[dtype]
    return jnp.dtype(dtype).type


def dtype_name(dtype) -> str:
    return str(np.dtype(dtype))


def is_floating_point(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.floating)


def is_integer(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.integer)


def is_complex(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.complexfloating)


# default dtype management (paddle.set_default_dtype)
_default_dtype = float32


def set_default_dtype(dtype) -> None:
    global _default_dtype
    dtype = convert_dtype(dtype)
    if not is_floating_point(dtype):
        raise TypeError("default dtype must be floating point")
    _default_dtype = dtype


def get_default_dtype():
    return _default_dtype
