"""Static-graph IR builder — the TPU-native Program/Block/Operation layer.

Parity anchors: the reference's two graph IRs — legacy ``ProgramDesc/BlockDesc/OpDesc``
(/root/reference/paddle/fluid/framework/framework.proto) and the PIR ``Program/Block/
Operation`` SSA IR (/root/reference/paddle/pir/include/core/operation.h:66,
program.h, block.h) — plus the op-building path used by static mode
(/root/reference/python/paddle/base/framework.py append_op).

TPU-native redesign: the IR is *lazy op recording* over the one runtime op registry
(core/op_registry.py). Calling any framework op on a symbolic ``Variable`` appends an
``Operation`` holding the op's pure jax function; shape/dtype inference (the
reference's InferMeta, phi/infermeta/*) is ``jax.eval_shape`` over that same function
— one source of truth, no YAML codegen, no separate infer-meta library. Execution
(static/executor.py) replays the recorded ops inside ``jax.jit``, so the "graph
compiler" is XLA itself: the reference's PIR passes + CINN lowering collapse into
XLA's fusion pipeline, and static/passes.py keeps only the graph-level passes that
matter pre-XLA (DCE / constant-fold / CSE — cf.
fluid/pir/transforms/general/{dead_code_elimination,constant_folding,cse}).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np

from .tensor import Tensor

__all__ = [
    "Variable", "Operation", "Block", "Program", "program_guard",
    "default_main_program", "default_startup_program", "building",
    "record_op", "enable_static_mode", "disable_static_mode", "static_mode_enabled",
]


# op-type keywords marking nondeterministic ops (never folded/CSEd; replayed
# under a per-run rng_guard by the Executor)
STOCHASTIC_KEYWORDS = ("rand", "normal", "uniform", "dropout", "bernoulli",
                       "poisson", "multinomial", "exponential", "randint",
                       "randperm", "shuffle")


class Variable(Tensor):
    """A symbolic tensor inside a Program. ``_data`` holds a jax.ShapeDtypeStruct
    (advisory shapes; -1/None dims are inferred at run time from real feeds)."""

    def __init__(self):  # pragma: no cover - use Variable.create
        raise TypeError("use Variable.create()")

    @classmethod
    def create(cls, shape, dtype, name: str, block: "Block",
               op: Optional["Operation"] = None, out_idx: int = 0,
               is_feed: bool = False):
        v = cls.__new__(cls)
        shape = tuple(-1 if s is None else int(s) for s in shape)
        adv = tuple(1 if s == -1 else s for s in shape)
        try:
            dt = jax.numpy.dtype(dtype)
        except TypeError:
            dt = dtype  # jax extended dtype (PRNG key avals from traced imports)
        v._data = jax.ShapeDtypeStruct(adv, dt)
        v.stop_gradient = True
        v._grad = None
        v._node = None
        v._out_idx = out_idx
        v.name = name
        v.persistable = False
        v._hooks = None
        v.is_parameter = False
        v.block = block
        v.op = op
        v.is_feed = is_feed
        v.decl_shape = shape  # may contain -1
        return v

    @property
    def shape(self):
        return list(self.decl_shape)

    def numpy(self):
        raise RuntimeError(
            f"Variable '{self.name}' is symbolic — fetch it through Executor.run()")

    item = numpy

    def __bool__(self):
        raise RuntimeError(
            "cannot branch on a symbolic Variable; static graphs require "
            "value-free Python control flow (use lax.cond-style ops)")

    def __repr__(self):
        return (f"Variable(name={self.name}, shape={self.decl_shape}, "
                f"dtype={self._data.dtype})")

    __str__ = __repr__


class Operation:
    """One recorded op: a pure jax function + its argument template.

    ``args`` entries may be Variable (symbolic input), Tensor (captured eager
    value, late-bound at replay so parameter updates are visible), or plain
    python literals. Cf. pir::Operation (operation.h:66) — here the "opcode" is
    the python callable itself.
    """

    __slots__ = ("idx", "type", "fn", "args", "kwargs", "inputs", "captured",
                 "outputs", "src")

    def __init__(self, idx, type, fn, args, kwargs, src=None):
        self.idx = idx
        self.type = type
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.src = src  # "file:line" of the recording call site (diagnostics)
        self.inputs: List[Variable] = [a for a in args if isinstance(a, Variable)]
        self.captured: List[Tensor] = [
            a for a in args if isinstance(a, Tensor) and not isinstance(a, Variable)]
        self.outputs: List[Variable] = []

    def _with_fn(self, type: str, fn) -> "Operation":
        """A copy of this op with a substituted kernel (same args/kwargs/
        outputs) — used by Program.clone(for_test=True) to swap train-mode
        kernels for their eval counterparts."""
        op = Operation.__new__(Operation)
        op.idx = self.idx
        op.type = type
        op.fn = fn
        op.args = self.args
        op.kwargs = self.kwargs
        op.src = self.src
        op.inputs = self.inputs
        op.captured = self.captured
        op.outputs = self.outputs
        return op

    def to_string(self):
        ins = ", ".join(v.name for v in self.inputs)
        caps = ", ".join(t.name for t in self.captured)
        outs = ", ".join(f"{v.name}:{v._data.dtype}{list(v._data.shape)}"
                         for v in self.outputs)
        extra = f" captured=[{caps}]" if caps else ""
        return f"  ({outs}) = {self.type}({ins}){extra}"


class Block:
    """A straight-line list of operations + declared variables
    (cf. pir/include/core/block.h; control flow stays inside ops as lax
    primitives, so nested blocks are not needed)."""

    def __init__(self, program: "Program", idx: int = 0):
        self.program = program
        self.idx = idx
        self.ops: List[Operation] = []
        self.vars: Dict[str, Variable] = {}

    def var(self, name: str) -> Variable:
        return self.vars[name]

    def create_var(self, shape, dtype, name=None, is_feed=False, op=None, out_idx=0):
        if name is None:
            name = self.program._next_name("tmp")
        v = Variable.create(shape, dtype, name, self, op=op, out_idx=out_idx,
                            is_feed=is_feed)
        self.vars[name] = v
        return v


class Program:
    """A recorded computation graph (cf. pir/include/core/program.h and the
    legacy ProgramDesc)."""

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.random_seed = 0
        self._name_counter = 0
        self._version = 0
        self._loss: Optional[Variable] = None
        self._optimizer = None
        self._grad_vars: Dict[int, Variable] = {}  # id(param Tensor) -> grad Variable
        self._is_test = False

    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[-1]

    def _next_name(self, prefix: str) -> str:
        self._name_counter += 1
        return f"{prefix}_{self._name_counter}"

    def list_vars(self):
        for b in self.blocks:
            yield from b.vars.values()

    @property
    def num_ops(self):
        return sum(len(b.ops) for b in self.blocks)

    def clone(self, for_test: bool = False) -> "Program":
        p = Program()
        p.random_seed = self.random_seed
        p._name_counter = self._name_counter
        p._is_test = for_test
        # pass/training state travels with the clone (aliases/folded constants
        # keep CSE'd and folded programs executable; loss/optimizer keep a
        # minimize()d program training)
        p._aliases = dict(getattr(self, "_aliases", {}))
        p._folded = dict(getattr(self, "_folded", {}))
        p._seed_stamps = dict(getattr(self, "_seed_stamps", {}))
        # analysis liveness roots (trace imports) travel with the clone too
        if getattr(self, "_outputs", None):
            p._outputs = list(self._outputs)
        if not for_test:
            # a test clone must never train: leaving loss/optimizer behind
            # keeps Executor.run on the inference path (no grads, no step())
            p._loss = self._loss
            p._optimizer = self._optimizer
            p._grad_vars = dict(self._grad_vars)
        blk, src = p.global_block(), self.global_block()
        blk.vars = dict(src.vars)
        blk.ops = list(src.ops)
        if for_test:
            # test clone: train-only stochastic ops swap to their eval kernels
            # (cf. reference clone(for_test=True) switching op test-mode
            # attrs); ops stay in place so their output Variables remain
            # defined. alpha_dropout's eval form is identity.
            from ..nn.functional.common import dropout_eval_kernel

            eval_kernels = {
                "dropout": dropout_eval_kernel,
                "alpha_dropout": lambda a, **k: a,
            }
            blk.ops = [
                op._with_fn(op.type + "_eval", eval_kernels[op.type])
                if op.type in eval_kernels else op
                for op in blk.ops
            ]
        return p

    def to_string(self, throw_on_error=False, with_details=False) -> str:
        lines = [f"{{ // block 0 (ops={self.num_ops})"]
        feeds = [v.name for v in self.list_vars() if getattr(v, "is_feed", False)]
        if feeds:
            lines.append(f"  feed: {', '.join(feeds)}")
        for op in self.global_block().ops:
            lines.append(op.to_string())
        lines.append("}")
        return "\n".join(lines)

    __str__ = to_string

    def all_parameters(self):
        seen, out = set(), []
        for op in self.global_block().ops:
            for t in op.captured:
                if t.is_parameter and id(t) not in seen:
                    seen.add(id(t))
                    out.append(t)
        return out

    def diagnose(self, targets=None, parameters=None):
        """Run the full program-level analysis suite (static/analysis) and
        return the AnalysisReport: shape/dtype verification, trace hazards,
        SPMD consistency, graph health (dead ops, duplicate subgraphs, unused
        parameters). Reports only — the program is never mutated."""
        from ..static.analysis import run_analysis

        return run_analysis(self, targets=targets, parameters=parameters)


# ---------------------------------------------------------------------------
# builder state
# ---------------------------------------------------------------------------

_program_stack: List[Program] = []
_default_main = [None]
_default_startup = [None]
_static_mode = [False]


def default_main_program() -> Program:
    if _default_main[0] is None:
        _default_main[0] = Program()
    return _default_main[0]


def default_startup_program() -> Program:
    if _default_startup[0] is None:
        _default_startup[0] = Program()
    return _default_startup[0]


def enable_static_mode():
    _static_mode[0] = True


def disable_static_mode():
    _static_mode[0] = False
    _program_stack.clear()


def static_mode_enabled() -> bool:
    return _static_mode[0]


def current_program() -> Program:
    if _program_stack:
        return _program_stack[-1]
    return default_main_program()


class program_guard:
    """``with program_guard(main, startup):`` — record into ``main``
    (reference: python/paddle/static/__init__.py program_guard)."""

    def __init__(self, main_program: Program, startup_program: Optional[Program] = None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        _program_stack.append(self.main)
        return self.main

    def __exit__(self, *exc):
        _program_stack.pop()
        return False


def building() -> bool:
    """Is at least one program open for recording? (op_registry fast-path check
    is on symbolic args, not this — see record_op caller.)"""
    return bool(_program_stack) or _static_mode[0]


def recording_constants() -> bool:
    """Record tensor-input-free (creation) ops too? Only inside an explicit
    program_guard in static mode — library internals outside a guard (layer
    init, buffers) stay eager."""
    return _static_mode[0] and bool(_program_stack)


# ---------------------------------------------------------------------------
# op recording (called from core/op_registry.apply_fn)
# ---------------------------------------------------------------------------

def _adv_struct(a):
    """Argument as seen by jax.eval_shape."""
    if isinstance(a, Variable):
        return a._data
    if isinstance(a, Tensor):
        return jax.ShapeDtypeStruct(tuple(a._data.shape), a._data.dtype)
    return a


_PKG_DIR = None
_EXTERNAL_FILE: Dict[str, bool] = {}  # co_filename -> outside paddle_tpu?


def _caller_src():
    """file:line of the first stack frame outside paddle_tpu — the user call
    site that recorded the op. Lets diagnostics name the offending source line
    (cf. the reference's op attrs op_callstack). Runs per recorded op, so the
    inside/outside-package verdict is cached per co_filename."""
    global _PKG_DIR
    import os
    import sys

    if _PKG_DIR is None:
        _PKG_DIR = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))) + os.sep
    try:
        f = sys._getframe(2)
        depth = 0
        while f is not None and depth < 32:
            fn = f.f_code.co_filename
            ext = _EXTERNAL_FILE.get(fn)
            if ext is None:
                ext = not os.path.abspath(fn).startswith(_PKG_DIR)
                _EXTERNAL_FILE[fn] = ext
            if ext:
                return f"{fn}:{f.f_lineno}"
            f = f.f_back
            depth += 1
    except Exception:
        pass
    return None


def record_op(name: str, fn, args, kwargs):
    """Append an Operation to the current program; return symbolic outputs."""
    prog = None
    for a in args:
        if isinstance(a, Variable):
            prog = a.block.program
            break
    if prog is None:
        prog = current_program()
    blk = prog.current_block()
    op = Operation(len(blk.ops), name, fn, list(args), dict(kwargs),
                   src=_caller_src())
    blk.ops.append(op)
    prog._version += 1
    if any(k in name for k in STOCHASTIC_KEYWORDS):
        # stamp seededness AT RECORD TIME, per op: a later unrelated
        # paddle.seed() must not launder an unreproducible recording past the
        # trace linter, and an op with no stamp (hand-built) falls back to
        # process state there.
        from ..framework.random import explicitly_seeded

        if not hasattr(prog, "_seed_stamps"):
            prog._seed_stamps = {}
        prog._seed_stamps[id(op)] = not (explicitly_seeded()
                                         or prog.random_seed)

    # advisory shape/dtype inference == InferMeta, via the op's own function
    def pure(*sym_args):
        full = list(args)
        it = iter(sym_args)
        for i, a in enumerate(full):
            if isinstance(a, (Variable, Tensor)):
                full[i] = next(it)
        return fn(*full, **kwargs)

    structs = [_adv_struct(a) for a in args if isinstance(a, (Variable, Tensor))]
    out_struct = jax.eval_shape(pure, *structs)
    single = not isinstance(out_struct, (tuple, list))
    out_list = [out_struct] if single else list(out_struct)
    outs = []
    for i, s in enumerate(out_list):
        v = blk.create_var(s.shape, s.dtype, name=prog._next_name(name),
                           op=op, out_idx=i)
        op.outputs.append(v)
        outs.append(v)
    return outs[0] if single else tuple(outs)
