"""Recurrent layers via ``lax.scan`` (reference: python/paddle/nn/layer/rnn.py).

The reference dispatches to cuDNN fused RNN kernels; on TPU the idiomatic form is a
``lax.scan`` over time with the gate matmuls batched onto the MXU — XLA pipelines the
scan body. Weight layout mirrors the reference: per layer/direction
weight_ih [gates*h, in], weight_hh [gates*h, h], bias_ih, bias_hh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.op_registry import apply_fn
from ...core.tensor import Tensor
from .. import initializer as I
from .layers import Layer


def _cell_step(mode, x, h, c, w_ih, w_hh, b_ih, b_hh, activation="tanh"):
    gates = x @ w_ih.T + h @ w_hh.T
    if b_ih is not None:
        gates = gates + b_ih + b_hh
    if mode == "LSTM":
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, c_new
    if mode == "GRU":
        r, z, n_ih = jnp.split(x @ w_ih.T + (b_ih if b_ih is not None else 0.0), 3, axis=-1)
        r_hh, z_hh, n_hh = jnp.split(h @ w_hh.T + (b_hh if b_hh is not None else 0.0), 3, axis=-1)
        r = jax.nn.sigmoid(r + r_hh)
        z = jax.nn.sigmoid(z + z_hh)
        n = jnp.tanh(n_ih + r * n_hh)
        h_new = (1 - z) * n + z * h
        return h_new, None
    act = jnp.tanh if activation == "tanh" else jax.nn.relu
    h_new = act(gates)
    return h_new, None


class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        self.bidirect = direction in ("bidirect", "bidirectional")
        num_dirs = 2 if self.bidirect else 1
        gates = {"LSTM": 4, "GRU": 3, "RNN": 1}[mode]
        self._all_weights = []
        for layer in range(num_layers):
            for d in range(num_dirs):
                in_sz = input_size if layer == 0 else hidden_size * num_dirs
                sfx = f"_l{layer}" + ("_reverse" if d else "")
                k = hidden_size ** -0.5
                w_ih = self.create_parameter([gates * hidden_size, in_sz], attr=weight_ih_attr,
                                             default_initializer=I.Uniform(-k, k))
                w_hh = self.create_parameter([gates * hidden_size, hidden_size], attr=weight_hh_attr,
                                             default_initializer=I.Uniform(-k, k))
                b_ih = self.create_parameter([gates * hidden_size], attr=bias_ih_attr, is_bias=True,
                                             default_initializer=I.Uniform(-k, k)) if bias_ih_attr is not False else None
                b_hh = self.create_parameter([gates * hidden_size], attr=bias_hh_attr, is_bias=True,
                                             default_initializer=I.Uniform(-k, k)) if bias_hh_attr is not False else None
                self.add_parameter(f"weight_ih{sfx}", w_ih)
                self.add_parameter(f"weight_hh{sfx}", w_hh)
                if b_ih is not None:
                    self.add_parameter(f"bias_ih{sfx}", b_ih)
                    self.add_parameter(f"bias_hh{sfx}", b_hh)
                self._all_weights.append((f"weight_ih{sfx}", f"weight_hh{sfx}",
                                          f"bias_ih{sfx}" if b_ih is not None else None,
                                          f"bias_hh{sfx}" if b_hh is not None else None))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        mode = self.mode
        num_dirs = 2 if self.bidirect else 1
        time_major = self.time_major
        nl, hs = self.num_layers, self.hidden_size
        act = self.activation

        weights = []
        for names in self._all_weights:
            weights.extend(self._parameters[n] if n is not None else None for n in names)
        flat_w = [w for w in weights if w is not None]
        has_bias = weights[2] is not None

        state_is_tuple = mode == "LSTM"
        if initial_states is not None:
            init_list = list(initial_states) if state_is_tuple else [initial_states]
        else:
            init_list = []

        def fn(x, *ws):
            ws = list(ws)
            widx = 0
            if init_list:
                if state_is_tuple:
                    h0_all, c0_all = ws[-2], ws[-1]
                    params = ws[:-2]
                else:
                    h0_all = ws[-1]
                    c0_all = None
                    params = ws[:-1]
            else:
                params = ws
                h0_all = c0_all = None
            xt = x if time_major else jnp.swapaxes(x, 0, 1)  # [T, B, F]
            B = xt.shape[1]
            h_states, c_states = [], []
            per_dir = 4 if has_bias else 2
            for layer in range(nl):
                outs = []
                for d in range(num_dirs):
                    base = (layer * num_dirs + d) * per_dir
                    w_ih, w_hh = params[base], params[base + 1]
                    b_ih = params[base + 2] if has_bias else None
                    b_hh = params[base + 3] if has_bias else None
                    li = layer * num_dirs + d
                    h0 = h0_all[li] if h0_all is not None else jnp.zeros((B, hs), xt.dtype)
                    c0 = c0_all[li] if c0_all is not None else jnp.zeros((B, hs), xt.dtype)
                    seq = jnp.flip(xt, 0) if d == 1 else xt

                    def step(carry, xi):
                        h, c = carry
                        h2, c2 = _cell_step(mode, xi, h, c, w_ih, w_hh, b_ih, b_hh, act)
                        return (h2, c2 if c2 is not None else c), h2

                    (hT, cT), ys = jax.lax.scan(step, (h0, c0), seq)
                    if d == 1:
                        ys = jnp.flip(ys, 0)
                    outs.append(ys)
                    h_states.append(hT)
                    c_states.append(cT)
                xt = outs[0] if num_dirs == 1 else jnp.concatenate(outs, axis=-1)
            out = xt if time_major else jnp.swapaxes(xt, 0, 1)
            h_final = jnp.stack(h_states, 0)
            if mode == "LSTM":
                return out, h_final, jnp.stack(c_states, 0)
            return out, h_final

        args = [inputs] + flat_w + init_list
        res = apply_fn("rnn_" + mode.lower(), fn, *args)
        if mode == "LSTM":
            out, h, c = res
            return out, (h, c)
        out, h = res
        return out, h


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh", **kwargs):
        super().__init__("RNN", input_size, hidden_size, num_layers, direction, time_major, dropout, activation, **kwargs)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        kwargs.pop("activation", None)
        super().__init__("LSTM", input_size, hidden_size, num_layers, direction, time_major, dropout, **kwargs)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        kwargs.pop("activation", None)
        super().__init__("GRU", input_size, hidden_size, num_layers, direction, time_major, dropout, **kwargs)


class _CellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None, init_value=0.0, batch_dim_idx=0):
        b = batch_ref.shape[batch_dim_idx]
        return Tensor(jnp.full((b, self.hidden_size), init_value, jnp.float32))


class SimpleRNNCell(_CellBase):
    def __init__(self, input_size, hidden_size, activation="tanh", **kwargs):
        super().__init__()
        self.hidden_size = hidden_size
        self.activation = activation
        k = hidden_size ** -0.5
        self.weight_ih = self.create_parameter([hidden_size, input_size], default_initializer=I.Uniform(-k, k))
        self.weight_hh = self.create_parameter([hidden_size, hidden_size], default_initializer=I.Uniform(-k, k))
        self.bias_ih = self.create_parameter([hidden_size], is_bias=True, default_initializer=I.Uniform(-k, k))
        self.bias_hh = self.create_parameter([hidden_size], is_bias=True, default_initializer=I.Uniform(-k, k))

    def forward(self, inputs, states=None):
        states = states if states is not None else self.get_initial_states(inputs)

        def fn(x, h, w_ih, w_hh, b_ih, b_hh):
            h2, _ = _cell_step("RNN", x, h, None, w_ih, w_hh, b_ih, b_hh, self.activation)
            return h2

        h = apply_fn("rnn_cell", fn, inputs, states, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh)
        return h, h


class LSTMCell(_CellBase):
    def __init__(self, input_size, hidden_size, **kwargs):
        super().__init__()
        self.hidden_size = hidden_size
        k = hidden_size ** -0.5
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size], default_initializer=I.Uniform(-k, k))
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size], default_initializer=I.Uniform(-k, k))
        self.bias_ih = self.create_parameter([4 * hidden_size], is_bias=True, default_initializer=I.Uniform(-k, k))
        self.bias_hh = self.create_parameter([4 * hidden_size], is_bias=True, default_initializer=I.Uniform(-k, k))

    def forward(self, inputs, states=None):
        if states is None:
            h = self.get_initial_states(inputs)
            c = self.get_initial_states(inputs)
        else:
            h, c = states

        def fn(x, hh, cc, w_ih, w_hh, b_ih, b_hh):
            return _cell_step("LSTM", x, hh, cc, w_ih, w_hh, b_ih, b_hh)

        h2, c2 = apply_fn("lstm_cell", fn, inputs, h, c, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh)
        return h2, (h2, c2)


class GRUCell(_CellBase):
    def __init__(self, input_size, hidden_size, **kwargs):
        super().__init__()
        self.hidden_size = hidden_size
        k = hidden_size ** -0.5
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size], default_initializer=I.Uniform(-k, k))
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size], default_initializer=I.Uniform(-k, k))
        self.bias_ih = self.create_parameter([3 * hidden_size], is_bias=True, default_initializer=I.Uniform(-k, k))
        self.bias_hh = self.create_parameter([3 * hidden_size], is_bias=True, default_initializer=I.Uniform(-k, k))

    def forward(self, inputs, states=None):
        states = states if states is not None else self.get_initial_states(inputs)

        def fn(x, h, w_ih, w_hh, b_ih, b_hh):
            h2, _ = _cell_step("GRU", x, h, None, w_ih, w_hh, b_ih, b_hh)
            return h2

        h = apply_fn("gru_cell", fn, inputs, states, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh)
        return h, h


class RNN(Layer):
    """Wraps a cell into a scan over time (reference nn.RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        xt = inputs if self.time_major else inputs.transpose([1, 0, 2])
        T = xt.shape[0]
        if self.is_reverse:
            from ...tensor.manipulation import flip

            xt = flip(xt, [0])
        outs = []
        state = initial_states
        for t in range(T):
            o, state = self.cell(xt[t], state)
            outs.append(o)
        from ...tensor.manipulation import stack

        out = stack(outs, 0)
        if self.is_reverse:
            from ...tensor.manipulation import flip

            out = flip(out, [0])
        if not self.time_major:
            out = out.transpose([1, 0, 2])
        return out, state
