"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""

from __future__ import annotations

from .. import functional as F
from .. import initializer as I
from .layers import Layer


def _make(name, fn_name, **fixed):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            self._kwargs = {**fixed}
            argnames = _ARG_NAMES.get(fn_name, [])
            for i, v in enumerate(args):
                self._kwargs[argnames[i]] = v
            for k, v in kwargs.items():
                if k != "name":
                    self._kwargs[k] = v

        def forward(self, x):
            return getattr(F, fn_name)(x, **self._kwargs)

    _Act.__name__ = name
    return _Act


_ARG_NAMES = {
    "leaky_relu": ["negative_slope"],
    "elu": ["alpha"],
    "celu": ["alpha"],
    "gelu": ["approximate"],
    "hardtanh": ["min", "max"],
    "hardshrink": ["threshold"],
    "softshrink": ["threshold"],
    "thresholded_relu": ["threshold", "value"],
    "softplus": ["beta", "threshold"],
    "softmax": ["axis"],
    "log_softmax": ["axis"],
    "maxout": ["groups", "axis"],
    "glu": ["axis"],
    "hardsigmoid": ["slope", "offset"],
}

ReLU = _make("ReLU", "relu")
ReLU6 = _make("ReLU6", "relu6")
LeakyReLU = _make("LeakyReLU", "leaky_relu")
ELU = _make("ELU", "elu")
CELU = _make("CELU", "celu")
SELU = _make("SELU", "selu")
GELU = _make("GELU", "gelu")
Silu = _make("Silu", "silu")
Swish = _make("Swish", "silu")
Mish = _make("Mish", "mish")
Hardswish = _make("Hardswish", "hardswish")
Hardsigmoid = _make("Hardsigmoid", "hardsigmoid")
Hardtanh = _make("Hardtanh", "hardtanh")
Hardshrink = _make("Hardshrink", "hardshrink")
Softshrink = _make("Softshrink", "softshrink")
Tanhshrink = _make("Tanhshrink", "tanhshrink")
ThresholdedReLU = _make("ThresholdedReLU", "thresholded_relu")
Softplus = _make("Softplus", "softplus")
Softsign = _make("Softsign", "softsign")
Sigmoid = _make("Sigmoid", "sigmoid")
LogSigmoid = _make("LogSigmoid", "logsigmoid")
Tanh = _make("Tanh", "tanh")
Softmax = _make("Softmax", "softmax")
LogSoftmax = _make("LogSoftmax", "log_softmax")
Maxout = _make("Maxout", "maxout")
GLU = _make("GLU", "glu")
RReLU = _make("RReLU", "rrelu")


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter([num_parameters], attr=weight_attr, default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)
