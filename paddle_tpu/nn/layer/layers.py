"""nn.Layer — module tree with parameters/buffers/hooks/state_dict.

Reference: python/paddle/nn/layer/layers.py:354 (class Layer). Same user contract
(named_parameters, sublayers, register_buffer, forward hooks, train/eval,
state_dict/set_state_dict), re-based on the framework Tensor/Parameter over jax.Array.
"""

from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ...core import dtype as dtype_mod
from ...core.tensor import Parameter, Tensor

_layer_counter = collections.defaultdict(int)


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        object.__setattr__(self, "_parameters", collections.OrderedDict())
        object.__setattr__(self, "_sub_layers", collections.OrderedDict())
        object.__setattr__(self, "_buffers", collections.OrderedDict())
        object.__setattr__(self, "_non_persistable_buffer_names", set())
        self.training = True
        self._dtype = dtype_mod.convert_dtype(dtype)
        cls = self.__class__.__name__.lower()
        _layer_counter[cls] += 1
        self._full_name = (name_scope or cls) + f"_{_layer_counter[cls] - 1}"
        self._forward_pre_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._hook_id = 0
        self._casted_by_pure_fp16 = False

    # ---- naming ----
    def full_name(self):
        return self._full_name

    # ---- attribute magic ----
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            for d in (layers, buffers):
                d.pop(name, None) if d else None
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            for d in (params, buffers):
                d.pop(name, None) if d else None
            layers[name] = value
            self.__dict__.pop(name, None)
        elif params is not None and name in params:
            if value is None:
                params.pop(name)
                object.__setattr__(self, name, None)
            elif isinstance(value, Tensor):
                params[name].set_value(value)
            else:
                raise TypeError(f"cannot assign {type(value)} to parameter {name}")
        elif buffers is not None and name in buffers:
            if value is None or isinstance(value, Tensor):
                buffers[name] = value
            else:
                raise TypeError(f"cannot assign {type(value)} to buffer {name}")
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + list(self._sub_layers) + list(self._buffers)

    # ---- registration API ----
    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor], persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        elif tensor is not None:
            tensor.persistable = True
        return tensor

    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias: bool = False,
        default_initializer=None,
    ) -> Parameter:
        from .. import initializer as I

        dtype = dtype_mod.convert_dtype(dtype) or self._dtype
        init = default_initializer
        name = None
        learning_rate = 1.0
        trainable = True
        if attr is not None and attr is not False:
            init = getattr(attr, "initializer", None) or init
            name = getattr(attr, "name", None)
            learning_rate = getattr(attr, "learning_rate", 1.0)
            trainable = getattr(attr, "trainable", True)
        if init is None:
            init = I.Constant(0.0) if is_bias else I.XavierNormal()
        data = init(shape, dtype)
        p = Parameter(data, dtype=dtype, name=name, trainable=trainable)
        p.optimize_attr["learning_rate"] = learning_rate
        if attr is not None and attr is not False:
            # per-param regularizer (overrides the optimizer-level
            # weight_decay — see Optimizer._decay_term)
            p.regularizer = getattr(attr, "regularizer", None)
        return p

    def create_tensor(self, name=None, dtype=None, persistable=False):
        import jax.numpy as jnp

        t = Tensor(jnp.zeros([], dtype_mod.convert_dtype(dtype) or self._dtype), name=name)
        t.persistable = persistable
        return t

    # ---- traversal ----
    def parameters(self, include_sublayers: bool = True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True, include_self=True) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (name + "." + pname if name else pname), p

    def buffers(self, include_sublayers: bool = True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True) -> Iterator[Tuple[str, Tensor]]:
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (name + "." + bname if name else bname), b

    def children(self) -> Iterator["Layer"]:
        for _, layer in self.named_children():
            yield layer

    def named_children(self) -> Iterator[Tuple[str, "Layer"]]:
        seen = set()
        for name, layer in self._sub_layers.items():
            if layer is not None and id(layer) not in seen:
                seen.add(id(layer))
                yield name, layer

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None) -> Iterator[Tuple[str, "Layer"]]:
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = prefix + ("." if prefix else "") + name
            yield from layer.named_sublayers(prefix=sub_prefix, include_self=True, layers_set=layers_set)

    def apply(self, fn):
        for layer in self.sublayers(include_self=True):
            fn(layer)
        return self

    # ---- mode ----
    def train(self):
        self.training = True
        for layer in self.sublayers():
            layer.training = True
        return self

    def eval(self):
        self.training = False
        for layer in self.sublayers():
            layer.training = False
        return self

    # ---- hooks ----
    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # ---- forward ----
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    # ---- state dict ----
    def state_dict(self, destination=None, include_sublayers=True, structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            dest[structured_name_prefix + name] = p
        for name, layer in self.named_sublayers(include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                key = (name + "." + bname) if name else bname
                dest[structured_name_prefix + key] = b
        return dest

    to_static_state_dict = state_dict

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            tgt = own[k]
            arr = v._data if isinstance(v, Tensor) else v
            arr = np.asarray(arr)
            if tuple(arr.shape) != tuple(tgt.shape):
                raise ValueError(f"shape mismatch for {k}: {arr.shape} vs {tgt.shape}")
            tgt.set_value(arr.astype(np.dtype(tgt.dtype)) if arr.dtype != tgt.dtype else arr)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # ---- dtype/device movement ----
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._cast_all(dtype_mod.convert_dtype(dtype))
        return self

    def astype(self, dtype):
        self._cast_all(dtype_mod.convert_dtype(dtype))
        return self

    def _cast_all(self, dtype, floating_only=True):
        import jax.numpy as jnp

        for p in self.parameters():
            if not floating_only or jnp.issubdtype(p.dtype, jnp.floating):
                p._data = p._data.astype(dtype)
        for _, b in self.named_buffers():
            if not floating_only or jnp.issubdtype(b.dtype, jnp.floating):
                b._data = b._data.astype(dtype)
        for layer in self.sublayers(include_self=True):
            layer._dtype = dtype

    def float(self):
        self._cast_all(dtype_mod.float32)
        return self

    def bfloat16(self):
        self._cast_all(dtype_mod.bfloat16)
        return self

    def half(self):
        self._cast_all(dtype_mod.float16)
        return self

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            mod_str = repr(layer)
            mod_str = "\n  ".join(mod_str.split("\n"))
            lines.append(f"({name}): {mod_str}")
        main = self.__class__.__name__ + "("
        if extra:
            main += extra
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        n = len(self._sub_layers)
        return self._sub_layers[str(idx % n if idx < 0 else idx)]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], collections.OrderedDict):
            for name, l in layers[0].items():
                self.add_sublayer(name, l)
        else:
            for i, l in enumerate(layers):
                if isinstance(l, tuple):
                    self.add_sublayer(l[0], l[1])
                else:
                    self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self
