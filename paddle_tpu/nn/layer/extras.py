"""Breadth-completion layers (reference: python/paddle/nn/layer/ — loss.py,
pooling.py, common.py, rnn.py dynamic_decode/BeamSearchDecoder, norm.py
SpectralNorm)."""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor, unwrap
from ..functional import extras as FX
from .. import initializer as I
from .layers import Layer

__all__ = [
    "PairwiseDistance", "Softmax2D", "Unflatten", "FeatureAlphaDropout",
    "ZeroPad1D", "ZeroPad3D", "LayerDict",
    "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D",
    "LPPool1D", "LPPool2D", "FractionalMaxPool2D", "FractionalMaxPool3D",
    "SoftMarginLoss", "MultiLabelSoftMarginLoss", "MultiMarginLoss",
    "PoissonNLLLoss", "GaussianNLLLoss", "TripletMarginWithDistanceLoss",
    "CTCLoss", "RNNTLoss", "HSigmoidLoss", "AdaptiveLogSoftmaxWithLoss",
    "SpectralNorm", "RNNCellBase", "BiRNN", "BeamSearchDecoder",
    "dynamic_decode",
]


# ---------------------------------------------------------------------------
# simple wrappers
# ---------------------------------------------------------------------------

class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return FX.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


class Softmax2D(Layer):
    def forward(self, x):
        return FX.softmax_2d(x)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self.shape_ = axis, shape

    def forward(self, x):
        from ...tensor import unflatten

        return unflatten(x, self.axis, self.shape_)


class FeatureAlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return FX.feature_alpha_dropout(x, self.p, self.training)


class ZeroPad1D(Layer):
    def __init__(self, padding, data_format="NCL", name=None):
        super().__init__()
        self.padding = padding
        self.data_format = data_format

    def forward(self, x):
        from ..functional import pad

        return pad(x, self.padding, mode="constant", value=0.0,
                   data_format=self.data_format)


class ZeroPad3D(ZeroPad1D):
    def __init__(self, padding, data_format="NCDHW", name=None):
        super().__init__(padding, data_format, name)


class LayerDict(Layer):
    """Dict container (reference: nn/layer/container.py LayerDict)."""

    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def keys(self):
        return self._sub_layers.keys()

    def values(self):
        return self._sub_layers.values()

    def items(self):
        return self._sub_layers.items()

    def update(self, sublayers):
        for k, v in (sublayers.items() if isinstance(sublayers, dict)
                     else sublayers):
            self[k] = v

    def pop(self, key):
        layer = self._sub_layers[key]
        del self._sub_layers[key]
        return layer


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------

class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCL",
                 output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding)
        self.output_size = output_size

    def forward(self, x, indices):
        k, s, p = self.args
        return FX.max_unpool1d(x, indices, k, s, p, self.output_size)


class MaxUnPool2D(MaxUnPool1D):
    def forward(self, x, indices):
        k, s, p = self.args
        return FX.max_unpool2d(x, indices, k, s, p, self.output_size)


class MaxUnPool3D(MaxUnPool1D):
    def forward(self, x, indices):
        k, s, p = self.args
        return FX.max_unpool3d(x, indices, k, s, p, self.output_size)


class LPPool1D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self.a = (norm_type, kernel_size, stride, padding, ceil_mode,
                  data_format)

    def forward(self, x):
        from ..functional.pooling import lp_pool1d

        n, k, s, p, c, d = self.a
        return lp_pool1d(x, n, k, s, p, c, d)


class LPPool2D(LPPool1D):
    def forward(self, x):
        from ..functional.pooling import lp_pool2d

        n, k, s, p, c, d = self.a
        return lp_pool2d(x, n, k, s, p, c, d)


class FractionalMaxPool2D(Layer):
    """Fractional max pooling (Graham 2014): pseudo-random pooling-region
    boundaries targeting ``output_size`` (reference: nn/layer/pooling.py).
    Boundaries are drawn per call from the framework RNG unless random_u
    is fixed."""

    _ndim = 2

    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.random_u = random_u
        self.return_mask = return_mask

    def _boundaries(self, in_size, out_size):
        if self.random_u is not None:
            u = float(self.random_u)
        else:
            from ...framework.random import next_host_seed

            u = (next_host_seed() % 10000) / 10000.0
        alpha = in_size / out_size
        # ceil(alpha * (i + u)) boundaries (Graham's pseudo-random sequence),
        # clamped so every segment is non-empty: b[i] in [i, in - (out - i)]
        b = [0]
        for i in range(1, out_size):
            v = int(math.ceil(alpha * (i + u)))
            b.append(min(max(v, i), in_size - (out_size - i)))
        b.append(in_size)
        return b

    def forward(self, x):
        from ...core.op_registry import apply_fn

        n = self._ndim
        out_size = (self.output_size if isinstance(self.output_size,
                                                   (tuple, list))
                    else (self.output_size,) * n)
        arr_shape = tuple(x.shape)
        bounds = [self._boundaries(arr_shape[2 + d], out_size[d])
                  for d in range(n)]

        def fn(a):
            out = a
            for d in range(n):
                segs = []
                for i in range(len(bounds[d]) - 1):
                    lo, hi = bounds[d][i], bounds[d][i + 1]
                    sl = [slice(None)] * out.ndim
                    sl[2 + d] = slice(lo, hi)
                    segs.append(jnp.max(out[tuple(sl)], axis=2 + d,
                                        keepdims=True))
                out = jnp.concatenate(segs, axis=2 + d)
            return out

        return apply_fn("fractional_max_pool", fn, x)


class FractionalMaxPool3D(FractionalMaxPool2D):
    _ndim = 3


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------

class _LossBase(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction


class SoftMarginLoss(_LossBase):
    def forward(self, input, label):
        return FX.soft_margin_loss(input, label, self.reduction)


class MultiLabelSoftMarginLoss(_LossBase):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__(reduction)
        self.weight = weight

    def forward(self, input, label):
        return FX.multi_label_soft_margin_loss(input, label, self.weight,
                                               self.reduction)


class MultiMarginLoss(_LossBase):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__(reduction)
        self.p, self.margin, self.weight = p, margin, weight

    def forward(self, input, label):
        return FX.multi_margin_loss(input, label, self.p, self.margin,
                                    self.weight, self.reduction)


class PoissonNLLLoss(_LossBase):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__(reduction)
        self.log_input, self.full, self.epsilon = log_input, full, epsilon

    def forward(self, input, label):
        return FX.poisson_nll_loss(input, label, self.log_input, self.full,
                                   self.epsilon, self.reduction)


class GaussianNLLLoss(_LossBase):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean", name=None):
        super().__init__(reduction)
        self.full, self.epsilon = full, epsilon

    def forward(self, input, label, variance):
        return FX.gaussian_nll_loss(input, label, variance, self.full,
                                    self.epsilon, self.reduction)


class TripletMarginWithDistanceLoss(_LossBase):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__(reduction)
        self.distance_function = distance_function
        self.margin, self.swap = margin, swap

    def forward(self, input, positive, negative):
        return FX.triplet_margin_with_distance_loss(
            input, positive, negative, self.distance_function, self.margin,
            self.swap, self.reduction)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return FX.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                           self.blank, self.reduction, norm_by_times)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.0, reduction="mean",
                 name=None):
        super().__init__()
        self.blank, self.reduction = blank, reduction
        self.fastemit_lambda = fastemit_lambda

    def forward(self, logits, labels, input_lengths, label_lengths):
        return FX.rnnt_loss(logits, labels, input_lengths, label_lengths,
                            self.blank, self.fastemit_lambda, self.reduction)


class HSigmoidLoss(Layer):
    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        self.num_classes = num_classes
        self.weight = self.create_parameter(
            [num_classes - 1, feature_size],
            default_initializer=I.Normal(std=1.0 / math.sqrt(feature_size)))
        self.bias = (self.create_parameter([num_classes - 1],
                                           default_initializer=I.Constant(0.0))
                     if bias_attr is not False else None)

    def forward(self, input, label, path_table=None, path_code=None):
        return FX.hsigmoid_loss(input, label, self.num_classes, self.weight,
                                self.bias, path_table, path_code)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """Cluster-factored softmax for huge vocabularies
    (reference: nn/layer/loss.py AdaptiveLogSoftmaxWithLoss)."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        self.cutoffs = list(cutoffs) + [n_classes]
        self.n_clusters = len(self.cutoffs) - 1
        self.head_size = self.cutoffs[0] + self.n_clusters
        self.in_features = in_features
        self.n_classes = n_classes
        init = I.XavierUniform()
        self.head_weight = self.create_parameter(
            [in_features, self.head_size], default_initializer=init)
        self.head_bias = (self.create_parameter(
            [self.head_size], default_initializer=I.Constant(0.0))
            if head_bias else None)
        self.tail_weights = []
        for i in range(self.n_clusters):
            hsz = max(1, int(in_features // (div_value ** (i + 1))))
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            w1 = self.create_parameter([in_features, hsz],
                                       default_initializer=init)
            w2 = self.create_parameter([hsz, osz], default_initializer=init)
            self.tail_weights.append((w1, w2))
            setattr(self, f"tail_{i}_proj", w1)
            setattr(self, f"tail_{i}_out", w2)

    def _weights_flat(self):
        ws = [self.head_weight]
        if self.head_bias is not None:
            ws.append(self.head_bias)
        for w1, w2 in self.tail_weights:
            ws.extend([w1, w2])
        return ws

    def _split_weights(self, ws):
        it = iter(ws)
        head_w = next(it)
        head_b = next(it) if self.head_bias is not None else None
        tails = [(next(it), next(it)) for _ in range(self.n_clusters)]
        return head_w, head_b, tails

    def _head_logp(self, x, head_w, head_b):
        logits = jnp.matmul(x, head_w)
        if head_b is not None:
            logits = logits + head_b
        return jax.nn.log_softmax(logits, -1)

    def forward(self, input, label):
        from ...core.op_registry import apply_fn

        shortlist = self.cutoffs[0]

        def fn(x, y, *ws):
            x = x.astype(jnp.float32)
            head_w, head_b, tails = self._split_weights(ws)
            head_logp = self._head_logp(x, head_w, head_b)
            safe_y = jnp.clip(y, 0, shortlist - 1)
            lp = jnp.take_along_axis(head_logp, safe_y[:, None], 1)[:, 0]
            out = jnp.where(y < shortlist, lp, 0.0)
            for i in range(self.n_clusters):
                lo, hi = self.cutoffs[i], self.cutoffs[i + 1]
                in_cluster = (y >= lo) & (y < hi)
                w1, w2 = tails[i]
                tail_logp = jax.nn.log_softmax(
                    jnp.matmul(jnp.matmul(x, w1), w2), -1)
                rel = jnp.clip(y - lo, 0, hi - lo - 1)
                lp_tail = (head_logp[:, shortlist + i]
                           + jnp.take_along_axis(tail_logp, rel[:, None], 1)[:, 0])
                out = jnp.where(in_cluster, lp_tail, out)
            return out, -jnp.mean(out)

        return apply_fn("adaptive_log_softmax_with_loss", fn, input, label,
                        *self._weights_flat())

    def log_prob(self, input):
        from ...core.op_registry import apply_fn

        def fn(x, *ws):
            x = x.astype(jnp.float32)
            head_w, head_b, tails = self._split_weights(ws)
            head_logp = self._head_logp(x, head_w, head_b)
            parts = [head_logp[:, : self.cutoffs[0]]]
            for i in range(self.n_clusters):
                w1, w2 = tails[i]
                tail_logp = jax.nn.log_softmax(
                    jnp.matmul(jnp.matmul(x, w1), w2), -1)
                parts.append(
                    head_logp[:, self.cutoffs[0] + i: self.cutoffs[0] + i + 1]
                    + tail_logp)
            return jnp.concatenate(parts, -1)

        return apply_fn("adaptive_log_softmax_log_prob", fn, input,
                        *self._weights_flat())

    def predict(self, input):
        return Tensor(jnp.argmax(unwrap(self.log_prob(input)), -1))


# ---------------------------------------------------------------------------
# SpectralNorm
# ---------------------------------------------------------------------------

class SpectralNorm(Layer):
    """Power-iteration spectral normalization of a weight tensor
    (reference: nn/layer/norm.py SpectralNorm — forward(weight) returns
    weight / sigma_max)."""

    def __init__(self, weight_shape, axis=0, power_iters=1, epsilon=1e-12,
                 name=None, dtype="float32"):
        super().__init__()
        self.axis = axis
        self.power_iters = power_iters
        self.epsilon = epsilon
        h = int(weight_shape[axis])
        w = int(np.prod(weight_shape)) // h
        self.register_buffer(
            "weight_u", Tensor(jax.random.normal(jax.random.key(0), (h,))))
        self.register_buffer(
            "weight_v", Tensor(jax.random.normal(jax.random.key(1), (w,))))

    def forward(self, weight):
        from ...core.op_registry import apply_fn

        axis, iters, eps = self.axis, self.power_iters, self.epsilon

        def fn(wt, u, v):
            mat = jnp.moveaxis(wt, axis, 0).reshape(wt.shape[axis], -1)

            def norm(a):
                return a / (jnp.linalg.norm(a) + eps)

            for _ in range(max(iters, 1)):
                v = norm(mat.T @ u)
                u = norm(mat @ v)
            sigma = u @ mat @ v
            return wt / sigma, jax.lax.stop_gradient(u), jax.lax.stop_gradient(v)

        out, u_new, v_new = apply_fn("spectral_norm", fn, weight,
                                     self.weight_u, self.weight_v)
        if not isinstance(u_new._data, jax.core.Tracer):
            # persist power-iteration state so the estimate converges across
            # forwards (reference updates the u buffer each call)
            self.weight_u._data = u_new._data
            self.weight_v._data = v_new._data
        return out


# ---------------------------------------------------------------------------
# RNN extras
# ---------------------------------------------------------------------------

class RNNCellBase(Layer):
    """Base for user RNN cells (reference: nn/layer/rnn.py RNNCellBase)."""

    def get_initial_states(self, batch_ref, shape=None, dtype="float32",
                           init_value=0.0, batch_dim_idx=0):
        b = unwrap(batch_ref).shape[batch_dim_idx]
        hidden = shape or [self.state_shape]
        if isinstance(hidden, int):
            hidden = [hidden]
        mk = lambda h: Tensor(jnp.full((b, int(h)), init_value, jnp.float32))
        if len(hidden) == 1:
            return mk(hidden[0])
        return tuple(mk(h) for h in hidden)


class BiRNN(Layer):
    """Bidirectional wrapper over two cells (reference: nn/layer/rnn.py BiRNN)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        from .rnn import RNN

        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        st_fw, st_bw = (initial_states if initial_states is not None
                        else (None, None))
        out_fw, s_fw = self.rnn_fw(inputs, st_fw, sequence_length)
        out_bw, s_bw = self.rnn_bw(inputs, st_bw, sequence_length)
        from ...tensor import concat

        return concat([out_fw, out_bw], axis=-1), (s_fw, s_bw)


class BeamSearchDecoder:
    """Beam search over a cell + output layer (reference: nn/layer/rnn.py
    BeamSearchDecoder). Works with dynamic_decode."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def initialize(self, initial_cell_states):
        b = unwrap(initial_cell_states[0] if isinstance(
            initial_cell_states, (tuple, list)) else initial_cell_states).shape[0]
        k = self.beam_size
        tokens = jnp.full((b, k), self.start_token, jnp.int32)
        log_probs = jnp.tile(jnp.asarray([[0.0] + [-1e9] * (k - 1)]), (b, 1))
        finished = jnp.zeros((b, k), bool)

        def tile(s):
            a = unwrap(s)
            return Tensor(jnp.repeat(a, k, axis=0))  # [b*k, ...]

        states = jax.tree_util.tree_map(
            tile, initial_cell_states,
            is_leaf=lambda x: isinstance(x, Tensor))
        return (tokens, log_probs, finished), states

    def step(self, time, inputs, states):
        b, k = inputs[0].shape[0], self.beam_size
        tokens, log_probs, finished = inputs
        flat_tok = Tensor(tokens.reshape(-1))
        emb = (self.embedding_fn(flat_tok) if self.embedding_fn
               else flat_tok)
        out, new_states = self.cell(emb, states)
        logits = self.output_fn(out) if self.output_fn else out
        logp = jax.nn.log_softmax(unwrap(logits).astype(jnp.float32), -1)
        V = logp.shape[-1]
        logp = logp.reshape(b, k, V)
        # finished beams only extend with end_token at zero cost
        pad = jnp.full((V,), -1e9).at[self.end_token].set(0.0)
        logp = jnp.where(finished[..., None], pad[None, None], logp)
        total = log_probs[..., None] + logp  # [b, k, V]
        flat = total.reshape(b, k * V)
        top_lp, top_idx = jax.lax.top_k(flat, k)
        beam_idx = top_idx // V
        tok = (top_idx % V).astype(jnp.int32)
        fin = jnp.take_along_axis(finished, beam_idx, 1) | (tok == self.end_token)

        def pick(s):
            a = unwrap(s).reshape((b, k) + unwrap(s).shape[1:])
            sel = jnp.take_along_axis(
                a, beam_idx.reshape((b, k) + (1,) * (a.ndim - 2)), 1)
            return Tensor(sel.reshape((b * k,) + a.shape[2:]))

        new_states = jax.tree_util.tree_map(
            pick, new_states, is_leaf=lambda x: isinstance(x, Tensor))
        return (tok, top_lp, fin), new_states, tok, fin


def dynamic_decode(decoder, inits=None, max_step_num=100, output_time_major=False,
                   **kwargs):
    """Run a decoder to completion (reference: nn/layer/rnn.py dynamic_decode).
    Returns (token ids [B, beam, T], final log probs)."""
    inputs, states = decoder.initialize(inits)
    outs = []
    for t in range(int(max_step_num)):
        inputs, states, tok, fin = decoder.step(t, inputs, states)
        outs.append(tok)
        if bool(jnp.all(fin)):
            break
    ids = jnp.stack(outs, -1)  # [b, beam, T]
    if output_time_major:
        ids = jnp.moveaxis(ids, -1, 0)
    return Tensor(ids), Tensor(inputs[1])
