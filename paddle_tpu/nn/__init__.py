"""paddle_tpu.nn — layers, functional, initializers (reference: python/paddle/nn)."""

from . import functional, initializer  # noqa: F401
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from .layer.activation import *  # noqa: F401,F403
from .layer.common import *  # noqa: F401,F403
from .layer.conv import *  # noqa: F401,F403
from .layer.extras import *  # noqa: F401,F403
from .layer.layers import Layer, LayerList, ParameterList, Sequential  # noqa: F401
from .layer.loss import *  # noqa: F401,F403
from .layer.norm import *  # noqa: F401,F403
from .layer.pooling import *  # noqa: F401,F403
from .layer.rnn import (  # noqa: F401
    GRU,
    LSTM,
    RNN,
    GRUCell,
    LSTMCell,
    SimpleRNN,
    SimpleRNNCell,
)
from .layer.transformer import (  # noqa: F401
    MultiHeadAttention,
    Transformer,
    TransformerDecoder,
    TransformerDecoderLayer,
    TransformerEncoder,
    TransformerEncoderLayer,
)
