"""Functional forms for the nn breadth-completion layers (reference:
python/paddle/nn/functional — loss.py, pooling.py max_unpool*, ctc_loss,
rnnt_loss, gaussian_nll_loss, multi_margin_loss...)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core.op_registry import apply_fn
from ...core.tensor import Tensor, unwrap

__all__ = [
    "max_pool2d_with_mask", "max_unpool1d", "max_unpool2d", "max_unpool3d",
    "soft_margin_loss", "multi_label_soft_margin_loss", "multi_margin_loss",
    "poisson_nll_loss", "gaussian_nll_loss", "triplet_margin_with_distance_loss",
    "pairwise_distance", "ctc_loss", "rnnt_loss", "hsigmoid_loss",
    "softmax_2d", "feature_alpha_dropout",
    # final breadth completion
    "sequence_mask", "zeropad2d", "fractional_max_pool2d",
    "fractional_max_pool3d", "npair_loss", "margin_cross_entropy",
    "affine_grid", "grid_sample", "gather_tree", "temporal_shift",
    "class_center_sample", "sparse_attention",
    "adaptive_log_softmax_with_loss", "flash_attn_qkvpacked",
    "flash_attn_varlen_qkvpacked",
    "elu_", "hardtanh_", "leaky_relu_", "tanh_", "thresholded_relu_",
]


# ---------------------------------------------------------------------------
# max pool with indices + unpool (reference: nn/functional/pooling.py)
# ---------------------------------------------------------------------------

def _tup(v, n):
    return tuple(v) if isinstance(v, (tuple, list)) else (int(v),) * n


def _pool_with_mask(x, kernel, stride, padding, n, ceil_mode=False):
    """NC<spatial> max pool returning (out, flat_indices_into_spatial)."""
    if ceil_mode:
        raise NotImplementedError(
            "return_mask with ceil_mode is not supported — pad the input "
            "explicitly instead")
    kernel, stride = _tup(kernel, n), _tup(stride or kernel, n)
    pad = _tup(padding, n)

    def fn(a):
        spatial = a.shape[2:]
        if any(pad):
            # pad with a large finite minimum: patch extraction pads with 0
            # (which would beat negative inputs), and -inf would turn into
            # NaN inside the conv-based patch gather (0 * -inf)
            neg = (jnp.finfo(a.dtype).min / 2
                   if jnp.issubdtype(a.dtype, jnp.floating)
                   else jnp.iinfo(a.dtype).min)
            cfg = [(0, 0), (0, 0)] + [(p, p) for p in pad]
            a_p = jnp.pad(a, cfg, constant_values=neg)
        else:
            a_p = a
        patches = jax.lax.conv_general_dilated_patches(
            a_p, kernel, stride, [(0, 0)] * n)
        # patches: [N, C*prod(k), out_spatial...]; feature dim orders C-major
        N = patches.shape[0]
        C = a.shape[1]
        k = int(np.prod(kernel))
        out_sp = patches.shape[2:]
        pt = patches.reshape(N, C, k, *out_sp)
        out = jnp.max(pt, axis=2)
        win_arg = jnp.argmax(pt, axis=2)  # index within window (never -inf
        # unless the whole window is padding, which pooling shapes preclude)
        # convert window-local index -> global flat index in UNPADDED coords
        win_coords = jnp.unravel_index(win_arg, kernel)
        grids = jnp.meshgrid(*[jnp.arange(s) for s in out_sp], indexing="ij")
        flat = jnp.zeros_like(win_arg)
        mult = 1
        for d in reversed(range(n)):
            g = grids[d].reshape((1, 1) + out_sp)
            coord = g * stride[d] - pad[d] + win_coords[d]
            coord = jnp.clip(coord, 0, spatial[d] - 1)
            flat = flat + coord * mult
            mult *= spatial[d]
        return out, flat.astype(jnp.int32)

    return apply_fn("max_pool_with_mask", fn, x)


def max_pool2d_with_mask(x, kernel_size, stride=None, padding=0):
    return _pool_with_mask(x, kernel_size, stride, padding, 2)


def _unpool(x, indices, n, kernel, stride, padding, output_size):
    kernel = _tup(kernel, n)
    stride = _tup(stride or kernel, n)
    pad = _tup(padding, n)

    def fn(a, idx):
        N, C = a.shape[:2]
        in_sp = a.shape[2:]
        if output_size is not None:
            out_sp = tuple(int(s) for s in output_size)[-n:]
        else:
            out_sp = tuple((in_sp[d] - 1) * stride[d] - 2 * pad[d] + kernel[d]
                           for d in range(n))
        total = int(np.prod(out_sp))
        flat = jnp.zeros((N, C, total), a.dtype)
        flat = flat.at[
            jnp.arange(N)[:, None, None],
            jnp.arange(C)[None, :, None],
            idx.reshape(N, C, -1),
        ].set(a.reshape(N, C, -1))
        return flat.reshape((N, C) + out_sp)

    return apply_fn("max_unpool", fn, x, indices)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCL", name=None):
    return _unpool(x, indices, 1, kernel_size, stride, padding, output_size)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCHW", name=None):
    return _unpool(x, indices, 2, kernel_size, stride, padding, output_size)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCDHW", name=None):
    return _unpool(x, indices, 3, kernel_size, stride, padding, output_size)


# ---------------------------------------------------------------------------
# losses (reference: nn/functional/loss.py)
# ---------------------------------------------------------------------------

def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def soft_margin_loss(input, label, reduction="mean", name=None):
    def fn(x, y):
        return _reduce(jnp.log1p(jnp.exp(-y * x)), reduction)

    return apply_fn("soft_margin_loss", fn, input, label)


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    w = unwrap(weight) if weight is not None else None

    def fn(x, y):
        l = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
        if w is not None:
            l = l * w
        return _reduce(l.mean(-1), reduction)

    return apply_fn("multi_label_soft_margin_loss", fn, input, label)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    w = unwrap(weight) if weight is not None else None

    def fn(x, y):
        n, c = x.shape
        xy = jnp.take_along_axis(x, y[:, None], axis=1)
        m = jnp.maximum(0.0, margin - xy + x) ** p
        m = m.at[jnp.arange(n), y].set(0.0)
        if w is not None:
            m = m * w[y][:, None]  # per-sample scale by weight[label]
        return _reduce(m.sum(-1) / c, reduction)

    return apply_fn("multi_margin_loss", fn, input, label)


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def fn(x, y):
        if log_input:
            l = jnp.exp(x) - y * x
        else:
            l = x - y * jnp.log(x + epsilon)
        if full:
            stirling = y * jnp.log(y + epsilon) - y + 0.5 * jnp.log(
                2 * math.pi * jnp.maximum(y, 1.0))
            l = l + jnp.where(y > 1, stirling, 0.0)
        return _reduce(l, reduction)

    return apply_fn("poisson_nll_loss", fn, input, label)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    def fn(mu, y, var):
        var = jnp.maximum(var, epsilon)
        l = 0.5 * (jnp.log(var) + (y - mu) ** 2 / var)
        if full:
            l = l + 0.5 * math.log(2 * math.pi)
        return _reduce(l, reduction)

    return apply_fn("gaussian_nll_loss", fn, input, label, variance)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    def fn(a, b):
        d = a - b + epsilon
        return jnp.sum(jnp.abs(d) ** p, -1, keepdims=keepdim) ** (1.0 / p)

    return apply_fn("pairwise_distance", fn, x, y)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean", name=None):
    dist = distance_function or (lambda a, b: pairwise_distance(a, b))

    def fn(a, p_, n_):
        dp = unwrap(dist(Tensor(a), Tensor(p_)))
        dn = unwrap(dist(Tensor(a), Tensor(n_)))
        if swap:
            dn2 = unwrap(dist(Tensor(p_), Tensor(n_)))
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)

    return apply_fn("triplet_margin_with_distance_loss", fn,
                    input, positive, negative)


def softmax_2d(x, name=None):
    """Softmax over the channel dim of NCHW input (reference: Softmax2D)."""
    return apply_fn("softmax_2d", lambda a: jax.nn.softmax(a, axis=-3), x)


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """Alpha dropout over whole channels (SELU-preserving)."""
    if not training or p == 0.0:
        return x

    def fn(a):
        from ...framework.random import next_key

        alpha_p = -1.7580993408473766
        shape = (a.shape[0], a.shape[1]) + (1,) * (a.ndim - 2)
        keep = jax.random.bernoulli(next_key(), 1.0 - p, shape)
        q = 1.0 - p
        a_coef = (q + alpha_p ** 2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        return a_coef * jnp.where(keep, a, alpha_p) + b_coef

    return apply_fn("feature_alpha_dropout", fn, x)


# ---------------------------------------------------------------------------
# CTC loss (reference: nn/functional/loss.py ctc_loss over warpctc kernel)
# ---------------------------------------------------------------------------

def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False, name=None):
    """log-domain alpha recursion inside lax.scan — the TPU-native warpctc.

    log_probs: [T, B, C] (reference layout) log-softmaxed or raw logits;
    labels: [B, S] int; returns per-batch negative log likelihood.
    """

    def fn(lp, lab, in_len, lab_len):
        lp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        T, B, C = lp.shape
        S = lab.shape[1]
        # extended sequence: blank l1 blank l2 ... blank lS blank (len 2S+1)
        ext = jnp.full((B, 2 * S + 1), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
        ext_len = 2 * lab_len.astype(jnp.int32) + 1
        NEG = -1e30

        # allowed skip: ext[s] != ext[s-2] (and s odd positions only)
        skip_ok = jnp.concatenate(
            [jnp.zeros((B, 2), bool), ext[:, 2:] != ext[:, :-2]], axis=1)
        skip_ok = skip_ok & (jnp.arange(2 * S + 1)[None] % 2 == 1)

        emit0 = lp[0]  # [B, C]
        alpha0 = jnp.full((B, 2 * S + 1), NEG)
        alpha0 = alpha0.at[:, 0].set(jnp.take_along_axis(
            emit0, jnp.full((B, 1), blank), 1)[:, 0])
        alpha0 = alpha0.at[:, 1].set(jnp.where(
            lab_len > 0,
            jnp.take_along_axis(emit0, ext[:, 1:2], 1)[:, 0], NEG))

        def step(alpha, t):
            emit = jnp.take_along_axis(lp[t], ext, axis=1)  # [B, 2S+1]
            stay = alpha
            prev1 = jnp.concatenate([jnp.full((B, 1), NEG), alpha[:, :-1]], 1)
            prev2 = jnp.concatenate([jnp.full((B, 2), NEG), alpha[:, :-2]], 1)
            prev2 = jnp.where(skip_ok, prev2, NEG)
            new = jnp.logaddexp(jnp.logaddexp(stay, prev1), prev2) + emit
            # frozen past input length
            new = jnp.where(t < in_len[:, None], new, alpha)
            return new, None

        alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        last = jnp.take_along_axis(alpha, (ext_len - 1)[:, None], 1)[:, 0]
        last2 = jnp.take_along_axis(
            alpha, jnp.maximum(ext_len - 2, 0)[:, None], 1)[:, 0]
        nll = -jnp.logaddexp(last, last2)
        if reduction == "mean":
            return jnp.mean(nll / jnp.maximum(lab_len.astype(jnp.float32), 1))
        if reduction == "sum":
            return jnp.sum(nll)
        return nll

    return apply_fn("ctc_loss", fn, log_probs, labels, input_lengths,
                    label_lengths)


def rnnt_loss(logits, labels, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.0, reduction="mean", name=None):
    """RNN-T loss: alpha recursion over the (T, U) lattice via scan
    (reference: nn/functional/loss.py rnnt_loss over warprnnt).

    logits: [B, T, U+1, C]; labels: [B, U] int.
    """

    def fn(lg, lab, t_len, u_len):
        lp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
        B, T, U1, C = lp.shape
        U = U1 - 1
        blank_lp = lp[..., blank]  # [B, T, U+1]
        lab_lp = jnp.take_along_axis(
            lp[:, :, :U, :], lab[:, None, :, None].astype(jnp.int32), -1
        )[..., 0]  # [B, T, U]
        if fastemit_lambda:
            # FastEmit (arXiv:2010.11148): up-weight label-emission
            # transitions by (1 + lambda) to penalize delayed emissions
            lab_lp = lab_lp * (1.0 + fastemit_lambda)
        NEG = -1e30

        # alpha over diagonals: alpha[t, u]; scan over t, vector over u
        def step_t(alpha_prev, t):
            # alpha_prev: [B, U+1] = alpha[t-1, :]
            # horizontal (time) move: blank from alpha[t-1, u]
            from_blank = alpha_prev + blank_lp[:, t - 1, :]

            # vertical (label) moves within this t: sequential over u
            def step_u(carry, u):
                a = carry  # alpha[t, u-1... building]
                new = jnp.logaddexp(from_blank[:, u],
                                    a + lab_lp[:, t, u - 1])
                return new, new

            first = from_blank[:, 0]
            _, rest = jax.lax.scan(step_u, first, jnp.arange(1, U + 1))
            alpha_t = jnp.concatenate([first[:, None], rest.T], axis=1)
            alpha_t = jnp.where(t < t_len[:, None], alpha_t, alpha_prev)
            return alpha_t, None

        # t = 0 row: only label moves
        def init_u(carry, u):
            new = carry + lab_lp[:, 0, u - 1]
            return new, new

        a00 = jnp.zeros((B,), jnp.float32)
        _, rest0 = jax.lax.scan(init_u, a00, jnp.arange(1, U + 1))
        alpha0 = jnp.concatenate([a00[:, None], rest0.T], axis=1)
        mask_u = jnp.arange(U + 1)[None] <= u_len[:, None]
        alpha0 = jnp.where(mask_u, alpha0, NEG)

        alpha, _ = jax.lax.scan(step_t, alpha0, jnp.arange(1, T))
        final = jnp.take_along_axis(alpha, u_len[:, None].astype(jnp.int32), 1)[:, 0]
        # terminal blank emission at (t_len-1, u_len)
        t_idx = (t_len - 1).astype(jnp.int32)
        term = jnp.take_along_axis(
            jnp.take_along_axis(blank_lp, t_idx[:, None, None], 1)[:, 0],
            u_len[:, None].astype(jnp.int32), 1)[:, 0]
        nll = -(final + term)
        if reduction == "mean":
            return jnp.mean(nll)
        if reduction == "sum":
            return jnp.sum(nll)
        return nll

    return apply_fn("rnnt_loss", fn, logits, labels, input_lengths,
                    label_lengths)


# ---------------------------------------------------------------------------
# hierarchical sigmoid (reference: nn/functional/loss.py hsigmoid_loss —
# complete-binary-tree default paths)
# ---------------------------------------------------------------------------

def _tree_paths(num_classes):
    """Path (node ids, codes) per class in a complete binary tree with
    num_classes leaves and num_classes-1 internal nodes (heap layout)."""
    depth = int(np.ceil(np.log2(max(num_classes, 2))))
    paths, codes = [], []
    for c in range(num_classes):
        node = c + num_classes - 1  # leaf position in heap
        p, k = [], []
        while node > 0:
            parent = (node - 1) // 2
            p.append(parent)
            k.append(node == 2 * parent + 2)  # right child -> code 1
            node = parent
        p = p[::-1]
        k = k[::-1]
        while len(p) < depth:  # pad
            p.append(0)
            k.append(False)
        paths.append(p[:depth])
        codes.append(k[:depth])
    valid = []
    for c in range(num_classes):
        node = c + num_classes - 1
        d = 0
        while node > 0:
            node = (node - 1) // 2
            d += 1
        valid.append([i < d for i in range(depth)])
    return (np.asarray(paths, np.int32), np.asarray(codes, np.float32),
            np.asarray(valid, bool))


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False, name=None):
    """weight: [num_classes-1, feature]; bias: [num_classes-1].
    Custom trees: path_table [num_classes, depth] node ids (-1 padding) and
    path_code [num_classes, depth] (reference is_custom path)."""
    if path_table is not None:
        pt = np.asarray(unwrap(path_table))
        paths = np.maximum(pt, 0).astype(np.int32)
        codes = np.asarray(unwrap(path_code), np.float32)
        valid = pt >= 0
    else:
        paths, codes, valid = _tree_paths(int(num_classes))

    def fn(x, y, w, *b):
        p = jnp.asarray(paths)[y]      # [B, depth]
        c = jnp.asarray(codes)[y]      # [B, depth]
        v = jnp.asarray(valid)[y]      # [B, depth]
        wp = w[p]                      # [B, depth, feature]
        logits = jnp.einsum("bdf,bf->bd", wp, x)
        if b:
            logits = logits + b[0][p]
        sign = 1.0 - 2.0 * c           # code 0 -> +1, code 1 -> -1
        lp = jax.nn.log_sigmoid(sign * logits)
        return -jnp.sum(jnp.where(v, lp, 0.0), -1).mean()

    if bias is not None:
        return apply_fn("hsigmoid_loss", fn, input, label, weight, bias)
    return apply_fn("hsigmoid_loss", fn, input, label, weight)


# ---------------------------------------------------------------------------
# final breadth completion (reference: nn/functional/__init__.py remainder)
# ---------------------------------------------------------------------------

def _inplace(fn):
    def f(x, *args, **kwargs):
        out = fn(x, *args, **kwargs)
        return x._replace_(out._data, out._node, out._out_idx)

    f.__name__ = fn.__name__ + "_"
    return f


def elu_(x, alpha=1.0, name=None):
    from .activation import elu

    return _inplace(elu)(x, alpha)


def hardtanh_(x, min=-1.0, max=1.0, name=None):
    from .activation import hardtanh

    return _inplace(hardtanh)(x, min, max)


def leaky_relu_(x, negative_slope=0.01, name=None):
    from .activation import leaky_relu

    return _inplace(leaky_relu)(x, negative_slope)


def tanh_(x, name=None):
    from ...tensor import tanh

    return _inplace(tanh)(x)


def thresholded_relu_(x, threshold=1.0, value=0.0, name=None):
    from .activation import thresholded_relu

    return _inplace(thresholded_relu)(x, threshold, value)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """Length vector -> [*, maxlen] mask (reference: sequence_mask;
    default dtype int64 like the reference)."""
    from ...core import dtype as dtype_mod

    if maxlen is None:
        lens = unwrap(x)
        if isinstance(lens, jax.core.Tracer):
            raise ValueError(
                "sequence_mask under jit needs an explicit maxlen (the "
                "output shape cannot depend on traced values)")
        maxlen = int(np.max(np.asarray(lens)))
    n = int(maxlen)

    def fn(lens):
        m = jnp.arange(n)[None] < lens[..., None]
        return m.astype(dtype_mod.convert_dtype(dtype))

    return apply_fn("sequence_mask", fn, x)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    from .common import pad as F_pad

    return F_pad(x, padding, mode="constant", value=0.0,
                 data_format=data_format)


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    from ..layer.extras import FractionalMaxPool2D

    return FractionalMaxPool2D(output_size, kernel_size, random_u,
                               return_mask)(x)


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    from ..layer.extras import FractionalMaxPool3D

    return FractionalMaxPool3D(output_size, kernel_size, random_u,
                               return_mask)(x)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """N-pair loss (reference: nn/functional/loss.py npair_loss)."""

    def fn(a, p, y):
        reg = jnp.mean(jnp.sum(a * a, -1)) + jnp.mean(jnp.sum(p * p, -1))
        sim = a @ p.T  # [B, B]
        same = (y[:, None] == y[None, :]).astype(sim.dtype)
        same = same / jnp.maximum(same.sum(-1, keepdims=True), 1.0)
        logp = jax.nn.log_softmax(sim, -1)
        ce = -jnp.mean(jnp.sum(same * logp, -1))
        return ce + l2_reg * reg * 0.25

    return apply_fn("npair_loss", fn, anchor, positive, labels)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction="mean"):
    """ArcFace-family margin softmax (reference: margin_cross_entropy —
    cos(m1*theta + m2) - m3 on the target logit). Single-group; vocab-parallel
    sharding composes via GSPMD when logits carry a sharded axis."""

    def fn(lg, y):
        # clip strictly inside (-1, 1): arccos' blows up at the boundary and
        # autodiff would produce NaN grads for any logit that rounds to 1.0
        lgf = jnp.clip(lg.astype(jnp.float32), -1.0 + 1e-6, 1.0 - 1e-6)
        theta = jnp.arccos(jnp.take_along_axis(lgf, y[:, None], 1)[:, 0])
        target = jnp.cos(margin1 * theta + margin2) - margin3
        out = lgf.at[jnp.arange(lg.shape[0]), y].set(target) * scale
        logp = jax.nn.log_softmax(out, -1)
        nll = -jnp.take_along_axis(logp, y[:, None], 1)[:, 0]
        loss = _reduce(nll, reduction)
        if return_softmax:
            return loss, jax.nn.softmax(out, -1)
        return loss

    return apply_fn("margin_cross_entropy", fn, logits, label)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """2D affine sampling grid (reference: vision affine_grid)."""
    shape = [int(s) for s in (out_shape if not isinstance(out_shape, Tensor)
                              else np.asarray(out_shape._data))]

    def fn(th):
        n, _, h, w = shape
        if align_corners:
            ys = jnp.linspace(-1, 1, h)
            xs = jnp.linspace(-1, 1, w)
        else:
            ys = (jnp.arange(h) + 0.5) * 2 / h - 1
            xs = (jnp.arange(w) + 0.5) * 2 / w - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], -1)  # [h, w, 3]
        return jnp.einsum("nij,hwj->nhwi", th.astype(jnp.float32), base)

    return apply_fn("affine_grid", fn, theta)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Spatial sampling at grid coords (reference: grid_sample). NCHW input,
    grid [n, h, w, 2] in [-1, 1]."""

    def fn(a, g):
        n, c, h, w = a.shape
        gx, gy = g[..., 0], g[..., 1]
        if align_corners:
            fx = (gx + 1) * (w - 1) / 2
            fy = (gy + 1) * (h - 1) / 2
        else:
            fx = ((gx + 1) * w - 1) / 2
            fy = ((gy + 1) * h - 1) / 2
        import jax.scipy.ndimage as jndi

        order = 1 if mode == "bilinear" else 0
        mode_nd = {"zeros": "constant", "border": "nearest",
                   "reflection": "mirror"}[padding_mode]

        def sample_one(img, yy, xx):  # img [c, h, w]
            return jax.vmap(lambda ch: jndi.map_coordinates(
                ch, [yy.ravel(), xx.ravel()], order=order, mode=mode_nd,
                cval=0.0))(img).reshape(c, *yy.shape)

        return jax.vmap(sample_one)(a, fy, fx)

    return apply_fn("grid_sample", fn, x, grid)


def gather_tree(ids, parents):
    """Beam-search backtrace (reference: gather_tree). ids/parents:
    [max_time, batch, beam]."""

    def fn(i, p):
        T = i.shape[0]

        def back(carry, t):
            beams = carry  # [batch, beam] beam indices at t+1
            tok = jnp.take_along_axis(i[t], beams, -1)
            beams = jnp.take_along_axis(p[t], beams, -1)
            return beams, tok

        init = jnp.broadcast_to(jnp.arange(i.shape[2])[None], i.shape[1:])
        _, toks = jax.lax.scan(back, init, jnp.arange(T - 1, -1, -1))
        return toks[::-1]

    return apply_fn("gather_tree", fn, ids, parents)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """TSM temporal channel shift (reference: temporal_shift)."""

    def fn(a):
        if data_format == "NHWC":
            a = jnp.transpose(a, (0, 3, 1, 2))
        nt, c, h, w = a.shape
        n = nt // seg_num
        v = a.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate(
            [v[:, 1:, :fold], jnp.zeros_like(v[:, :1, :fold])], 1)
        right = jnp.concatenate(
            [jnp.zeros_like(v[:, :1, fold:2 * fold]), v[:, :-1, fold:2 * fold]], 1)
        rest = v[:, :, 2 * fold:]
        out = jnp.concatenate([left, right, rest], 2).reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out

    return apply_fn("temporal_shift", fn, x)


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample negative class centers + remap labels (reference:
    class_center_sample — PartialFC). Deterministic given the RNG stream."""
    from ...framework.random import next_key

    def fn(y):
        pos = jnp.zeros((num_classes,), bool).at[y].set(True)
        noise = jax.random.uniform(next_key(), (num_classes,))
        # positives first (score 2), then random negatives
        score = jnp.where(pos, 2.0, noise)
        _, chosen = jax.lax.top_k(score, num_samples)
        chosen = jnp.sort(chosen)
        # remap: label -> its index within chosen (positives always included)
        remap = jnp.zeros((num_classes,), jnp.int32).at[chosen].set(
            jnp.arange(num_samples, dtype=jnp.int32))
        return remap[y], chosen

    return apply_fn("class_center_sample", fn, label)


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Block-sparse attention with a CSR sparsity pattern (reference:
    nn/functional/sparse_attention.py over the CUDA kernel). TPU note: XLA
    has no CSR attention primitive — the pattern is materialized as a bias
    mask (correct; the perf path on TPU is flashmask/ring attention)."""

    def fn(q, k, v, off, cols, *masks):
        b, h, s, d = q.shape
        nnz = cols.shape[-1]

        def one_mask(off_bh, cols_bh):
            rows = jnp.repeat(jnp.arange(s), jnp.diff(off_bh).astype(jnp.int32),
                              total_repeat_length=nnz)
            # entries beyond off[-1] are padding: scatter False via max so
            # they can never switch a cell on
            valid = jnp.arange(nnz) < off_bh[-1]
            return jnp.zeros((s, s), bool).at[rows, cols_bh].max(valid)

        # per-(batch, head) CSR patterns
        mask = jax.vmap(jax.vmap(one_mask))(off, cols)  # [b, h, s, s]
        logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                            k.astype(jnp.float32)) / jnp.sqrt(float(d))
        logits = jnp.where(mask, logits, -1e9)
        it = iter(masks)
        if key_padding_mask is not None:
            kpm = next(it)  # [b, s]: 1/True = keep
            logits = jnp.where(kpm.astype(bool)[:, None, None, :], logits, -1e9)
        if attn_mask is not None:
            logits = logits + next(it).astype(jnp.float32)
        probs = jax.nn.softmax(logits, -1)
        return jnp.einsum("bhqk,bhkd->bhqd", probs, v.astype(jnp.float32)).astype(q.dtype)

    extra = [m for m in (key_padding_mask, attn_mask) if m is not None]
    return apply_fn("sparse_attention", fn, query, key, value,
                    sparse_csr_offset, sparse_csr_columns, *extra)


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """Functional form (reference: adaptive_log_softmax_with_loss).
    head_weight: [in, shortlist+n_clusters]; tail_weights: list of (w1, w2)."""

    cut = list(cutoffs)
    shortlist = cut[0]
    if len(cut) - 1 != len(tail_weights):
        raise ValueError(
            f"cutoffs must have len(tail_weights)+1 entries (the last one is "
            f"n_classes): got {len(cut)} cutoffs for {len(tail_weights)} tails")
    y_eager = unwrap(label)
    if not isinstance(y_eager, jax.core.Tracer):
        if bool((np.asarray(y_eager) < 0).any()) or bool(
                (np.asarray(y_eager) >= cut[-1]).any()):
            raise ValueError(
                f"labels must be in [0, {cut[-1]}) for these cutoffs")

    args = [input, label, head_weight]
    if head_bias is not None:
        args.append(head_bias)
    flat_tails = [w for pair in tail_weights for w in pair]
    args.extend(flat_tails)

    def fn(x, y, hw, *rest):
        it = iter(rest)
        hb = next(it) if head_bias is not None else None
        tails = [(next(it), next(it)) for _ in range(len(tail_weights))]
        x = x.astype(jnp.float32)
        logits = x @ hw
        if hb is not None:
            logits = logits + hb
        head_logp = jax.nn.log_softmax(logits, -1)
        safe_y = jnp.clip(y, 0, shortlist - 1)
        out = jnp.where(y < shortlist,
                        jnp.take_along_axis(head_logp, safe_y[:, None], 1)[:, 0],
                        0.0)
        for i, (w1, w2) in enumerate(tails):
            lo, hi = cut[i], cut[i + 1]
            in_cluster = (y >= lo) & (y < hi)
            tail_logp = jax.nn.log_softmax((x @ w1) @ w2, -1)
            rel = jnp.clip(y - lo, 0, hi - lo - 1)
            lp = (head_logp[:, shortlist + i]
                  + jnp.take_along_axis(tail_logp, rel[:, None], 1)[:, 0])
            out = jnp.where(in_cluster, lp, out)
        return out, -jnp.mean(out)

    return apply_fn("adaptive_log_softmax_with_loss_fn", fn, *args)


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False, return_softmax=False,
                         name=None):
    """Packed-qkv flash attention (reference: flash_attention.py
    flash_attn_qkvpacked). qkv: [b, s, 3, h, d]."""
    from .flash_attention import flash_attention
    from ...tensor import unbind

    q, k, v = unbind(qkv, axis=2)
    return flash_attention(q, k, v, dropout=dropout, causal=causal,
                           return_softmax=return_softmax)


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k, max_seqlen_q,
                                max_seqlen_k, scale=None, dropout=0.0,
                                causal=False, return_softmax=False, name=None):
    """Varlen packed flash attention (reference: flash_attention.py:792 over
    the CUDA varlen kernels). qkv: [total_tokens, 3, h, d] with cu_seqlens
    prefix sums. TPU-native: ONE segment-masked Pallas flash kernel call over
    the whole packed buffer (ops/flash_attention.flash_attention_varlen) —
    no per-sequence loop, no padding."""
    from ...ops.flash_attention import flash_attention_varlen

    pk = qkv._data if isinstance(qkv, Tensor) else jnp.asarray(qkv)
    cu = np.asarray(cu_seqlens_q._data if isinstance(cu_seqlens_q, Tensor)
                    else cu_seqlens_q).reshape(-1)
    total = pk.shape[0]
    # token i belongs to segment searchsorted(cu, i, 'right') - 1
    seg = jnp.asarray(np.searchsorted(cu, np.arange(total), side="right") - 1,
                      jnp.int32)[None]
    q, k, v = pk[None, :, 0], pk[None, :, 1], pk[None, :, 2]
    out = flash_attention_varlen(q, k, v, seg, seg, causal,
                                 None if scale is None else float(scale))
    # mirror flash_attention's (out, softmax|None) return convention
    return Tensor(out[0]), None
