"""Loss functionals (reference: python/paddle/nn/functional/loss.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.op_registry import AMP_BLACK, OpDef, apply_fn
from ...core.tensor import Tensor, unwrap

_XENT = OpDef("cross_entropy", None, amp=AMP_BLACK)


def _reduce(out, reduction):
    if reduction == "mean":
        return out.mean()
    if reduction == "sum":
        return out.sum()
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean", soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0, name=None):
    def fn(logits, lab, *w):
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis) if use_softmax else jnp.log(jnp.maximum(logits.astype(jnp.float32), 1e-30))
        n_class = logits.shape[axis]
        if soft_label:
            soft = lab
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / n_class
            loss = -(soft * lp).sum(axis=axis)
        else:
            li = lab.astype(jnp.int32)
            if li.ndim == lp.ndim:
                li = jnp.squeeze(li, axis=axis)
            oh = jax.nn.one_hot(li, n_class, axis=axis, dtype=lp.dtype)
            if label_smoothing > 0:
                oh = oh * (1 - label_smoothing) + label_smoothing / n_class
            loss = -(oh * lp).sum(axis=axis)
            valid = li != ignore_index
            loss = jnp.where(valid, loss, 0.0)
            if w:
                wt = jnp.take(w[0], jnp.maximum(li, 0))
                loss = loss * wt
                if reduction == "mean":
                    denom = jnp.maximum((wt * valid).sum(), 1e-12)
                    return loss.sum() / denom
            if reduction == "mean":
                denom = jnp.maximum(valid.sum(), 1)
                return loss.sum() / denom
        return _reduce(loss, reduction)

    args = [input, label] + ([weight] if weight is not None else [])
    return apply_fn("cross_entropy", fn, *args, _opdef=_XENT)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100, numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index, reduction="none", axis=axis)
    from .activation import softmax as _softmax

    loss = loss.unsqueeze(axis) if loss.ndim == (logits.ndim - 1) else loss
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    def fn(lp, lab, *w):
        li = lab.astype(jnp.int32)
        loss = -jnp.take_along_axis(lp, li[..., None] if lp.ndim == li.ndim + 1 else li, axis=-1 if lp.ndim == li.ndim + 1 else 1)
        loss = loss.squeeze(-1) if lp.ndim == li.ndim + 1 else loss
        valid = li != ignore_index
        loss = jnp.where(valid, loss, 0.0)
        if w:
            wt = jnp.take(w[0], jnp.maximum(li, 0))
            loss = loss * wt
            if reduction == "mean":
                return loss.sum() / jnp.maximum((wt * valid).sum(), 1e-12)
        if reduction == "mean":
            return loss.sum() / jnp.maximum(valid.sum(), 1)
        return _reduce(loss, reduction)

    args = [input, label] + ([weight] if weight is not None else [])
    return apply_fn("nll_loss", fn, *args)


def mse_loss(input, label, reduction="mean", name=None):
    return apply_fn("mse_loss", lambda a, b: _reduce(jnp.square(a - b), reduction), input, label)


def l1_loss(input, label, reduction="mean", name=None):
    return apply_fn("l1_loss", lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta) * delta
        return _reduce(loss, reduction)

    return apply_fn("smooth_l1_loss", fn, input, label)


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):
    def fn(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)

    return apply_fn("huber_loss", fn, input, label)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def fn(p, lab, *w):
        p32 = p.astype(jnp.float32)
        loss = -(lab * jnp.log(jnp.maximum(p32, 1e-12)) + (1 - lab) * jnp.log(jnp.maximum(1 - p32, 1e-12)))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)

    args = [input, label] + ([weight] if weight is not None else [])
    return apply_fn("binary_cross_entropy", fn, *args, _opdef=_XENT)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean", pos_weight=None, name=None):
    def fn(z, lab, *rest):
        z32 = z.astype(jnp.float32)
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = rest[i]
            i += 1
        if pos_weight is not None:
            pw = rest[i]
        log_sig = jax.nn.log_sigmoid(z32)
        log_sig_neg = jax.nn.log_sigmoid(-z32)
        if pw is not None:
            loss = -(pw * lab * log_sig + (1 - lab) * log_sig_neg)
        else:
            loss = -(lab * log_sig + (1 - lab) * log_sig_neg)
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    args = [logit, label] + [a for a in (weight, pos_weight) if a is not None]
    return apply_fn("bce_with_logits", fn, *args, _opdef=_XENT)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def fn(lp, t):
        if log_target:
            loss = jnp.exp(t) * (t - lp)
        else:
            loss = t * (jnp.log(jnp.maximum(t, 1e-12)) - lp)
        if reduction == "batchmean":
            return loss.sum() / lp.shape[0]
        return _reduce(loss, reduction)

    return apply_fn("kl_div", fn, input, label)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    def fn(a, b, lab):
        loss = jnp.maximum(-lab * (a - b) + margin, 0.0)
        return _reduce(loss, reduction)

    return apply_fn("margin_ranking_loss", fn, input, other, label)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def fn(a, lab):
        loss = jnp.where(lab == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(loss, reduction)

    return apply_fn("hinge_embedding_loss", fn, input, label)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def fn(a, b, lab):
        cos = (a * b).sum(-1) / jnp.maximum(jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(lab == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    return apply_fn("cosine_embedding_loss", fn, input1, input2, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6, swap=False, reduction="mean", name=None):
    def fn(a, pos, neg):
        dp = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        dn = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            dn2 = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return apply_fn("triplet_margin_loss", fn, input, positive, negative)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum", name=None):
    def fn(z, lab, *nrm):
        p = jax.nn.sigmoid(z)
        ce = -(lab * jax.nn.log_sigmoid(z) + (1 - lab) * jax.nn.log_sigmoid(-z))
        p_t = p * lab + (1 - p) * (1 - lab)
        loss = ce * ((1 - p_t) ** gamma)
        if alpha >= 0:
            a_t = alpha * lab + (1 - alpha) * (1 - lab)
            loss = a_t * loss
        if nrm:
            loss = loss / nrm[0]
        return _reduce(loss, reduction)

    args = [logit, label] + ([normalizer] if normalizer is not None else [])
    return apply_fn("sigmoid_focal_loss", fn, *args)


def log_loss(input, label, epsilon=1e-4, name=None):
    return apply_fn(
        "log_loss",
        lambda p, l: -l * jnp.log(p + epsilon) - (1 - l) * jnp.log(1 - p + epsilon),
        input,
        label,
    )


def square_error_cost(input, label):
    return apply_fn("square_error_cost", lambda a, b: jnp.square(a - b), input, label)


def dice_loss(input, label, epsilon=1e-5, name=None):
    def fn(p, l):
        l_oh = jax.nn.one_hot(l.squeeze(-1).astype(jnp.int32), p.shape[-1], dtype=p.dtype)
        inter = (p * l_oh).sum(axis=tuple(range(1, p.ndim)))
        union = p.sum(axis=tuple(range(1, p.ndim))) + l_oh.sum(axis=tuple(range(1, p.ndim)))
        return (1 - (2 * inter + epsilon) / (union + epsilon)).mean()

    return apply_fn("dice_loss", fn, input, label)
