"""Common functionals: linear, dropout, embedding, one_hot, interpolate, pad, cosine_sim.

Reference: python/paddle/nn/functional/common.py + input.py. The matmul in ``linear``
is the single most important op for MXU utilization — it lowers to a plain
``dot_general`` that XLA tiles onto the systolic array and fuses bias/activation into.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.op_registry import AMP_WHITE, OpDef, apply_fn
from ...core.tensor import Tensor, unwrap
from ...framework.random import next_key

_LINEAR = OpDef("linear", None, amp=AMP_WHITE)


def linear(x, weight, bias=None, name=None):
    if bias is None:
        return apply_fn("linear", lambda a, w: jnp.matmul(a, w), x, weight, _opdef=_LINEAR)
    return apply_fn("linear", lambda a, w, b: jnp.matmul(a, w) + b, x, weight, bias, _opdef=_LINEAR)


def dropout_eval_kernel(a, p=0.5, axis=None, mode="upscale_in_train"):
    """Test-mode dropout (also substituted in by Program.clone(for_test=True))."""
    return a if mode == "upscale_in_train" else a * (1 - p)


def dropout_train_kernel(a, p=0.5, axis=None, mode="upscale_in_train"):
    # key drawn INSIDE the kernel: under the static Executor's per-run
    # rng_guard (traced key) this yields fresh masks every run; eagerly it
    # advances the global stream exactly as before
    key = next_key()
    shape = list(a.shape)
    if axis is not None:
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        shape = [s if i in [ax % a.ndim for ax in axes] else 1
                 for i, s in enumerate(a.shape)]
    keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
    if mode == "upscale_in_train":
        return jnp.where(keep, a / (1.0 - p), jnp.zeros_like(a))
    return jnp.where(keep, a, jnp.zeros_like(a))


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    axis = list(axis) if isinstance(axis, (list, tuple)) else axis
    if not training or p == 0.0:
        return apply_fn("dropout_eval", dropout_eval_kernel, x, p=p, mode=mode)
    return apply_fn("dropout", dropout_train_kernel, x, p=p, axis=axis, mode=mode)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x

    def fn(a):
        key = next_key()  # inside the kernel: fresh under static rng_guard
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        a_coef = (q + alpha_p**2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        return a_coef * jnp.where(keep, a, alpha_p) + b_coef

    return apply_fn("alpha_dropout", fn, x)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    def fn(w, idx):
        out = jnp.take(w, idx, axis=0)
        if padding_idx is not None and padding_idx >= 0:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, jnp.zeros_like(out), out)
        return out

    return apply_fn("embedding", fn, weight, x if not isinstance(x, Tensor) else x.astype("int32"))


def one_hot(x, num_classes, name=None):
    return apply_fn("one_hot", lambda idx: jax.nn.one_hot(idx, num_classes, dtype=jnp.float32), x)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def fn(l, *pd):
        n = l.shape[-1]
        if pd:
            return (1 - epsilon) * l + epsilon * pd[0]
        return (1 - epsilon) * l + epsilon / n

    if prior_dist is not None:
        return apply_fn("label_smooth", fn, label, prior_dist)
    return apply_fn("label_smooth", fn, label)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def fn(a, b):
        num = jnp.sum(a * b, axis=axis)
        d1 = jnp.sqrt(jnp.sum(a * a, axis=axis))
        d2 = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return num / jnp.maximum(d1 * d2, eps)

    return apply_fn("cosine_similarity", fn, x1, x2)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def fn(a):
        nrm = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(nrm, epsilon)

    return apply_fn("normalize", fn, x)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    from ...tensor.manipulation import pad as _pad

    return _pad(x, pad, mode, value, data_format)


def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW", name=None):
    def fn(a):
        cf = data_format.startswith("NC")
        spatial = a.shape[2:] if cf else a.shape[1:-1]
        if size is not None:
            tgt = tuple(int(unwrap(s)) for s in (size if isinstance(size, (list, tuple)) else [size]))
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * len(spatial)
            tgt = tuple(int(s * f) for s, f in zip(spatial, sf))
        meth = {"nearest": "nearest", "bilinear": "linear", "trilinear": "linear", "bicubic": "cubic", "linear": "linear", "area": "linear"}[mode]
        if cf:
            new_shape = a.shape[:2] + tgt
        else:
            new_shape = (a.shape[0],) + tgt + (a.shape[-1],)
        return jax.image.resize(a, new_shape, method=meth)

    return apply_fn("interpolate", fn, x)


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def _pair(v):
        return tuple(v) if isinstance(v, (list, tuple)) else (v, v)

    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    ph, pw = _pair(paddings)
    dh, dw = _pair(dilations)

    def fn(a):
        n, c, h, w = a.shape
        a = jnp.pad(a, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
        oh = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        ow = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        cols = []
        for i in range(kh):
            for j in range(kw):
                patch = a[:, :, i * dh : i * dh + oh * sh : sh, j * dw : j * dw + ow * sw : sw]
                cols.append(patch)
        out = jnp.stack(cols, axis=2)  # n, c, kh*kw, oh, ow
        return out.reshape(n, c * kh * kw, oh * ow)

    return apply_fn("unfold", fn, x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def _pair(v):
        return tuple(v) if isinstance(v, (list, tuple)) else (v, v)

    oh, ow = _pair(output_sizes)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    ph, pw = _pair(paddings)
    dh, dw = _pair(dilations)

    def fn(a):
        n, ckk, L = a.shape
        c = ckk // (kh * kw)
        lh = (oh + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        lw = (ow + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        a = a.reshape(n, c, kh, kw, lh, lw)
        out = jnp.zeros((n, c, oh + 2 * ph, ow + 2 * pw), a.dtype)
        for i in range(kh):
            for j in range(kw):
                out = out.at[:, :, i * dh : i * dh + lh * sh : sh, j * dw : j * dw + lw * sw : sw].add(a[:, :, i, j])
        return out[:, :, ph : ph + oh, pw : pw + ow]

    return apply_fn("fold", fn, x)


def bilinear(x1, x2, weight, bias=None, name=None):
    def fn(a, b, w, *bb):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bb:
            out = out + bb[0]
        return out

    args = [x1, x2, weight] + ([bias] if bias is not None else [])
    return apply_fn("bilinear", fn, *args)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c // (r * r), r, r, h, w)
            a = a.transpose(0, 1, 4, 2, 5, 3)
            return a.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, r, r, c // (r * r))
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h * r, w * r, c // (r * r))

    return apply_fn("pixel_shuffle", fn, x)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c, h // r, r, w // r, r)
            a = a.transpose(0, 1, 3, 5, 2, 4)
            return a.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = a.shape
        a = a.reshape(n, h // r, r, w // r, r, c)
        a = a.transpose(0, 2, 4, 1, 3, 5).reshape(n, h // r, w // r, c * r * r)
        return a

    return apply_fn("pixel_unshuffle", fn, x)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            return a.reshape(n, groups, c // groups, h, w).transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)
        n, h, w, c = a.shape
        return a.reshape(n, h, w, groups, c // groups).transpose(0, 1, 2, 4, 3).reshape(n, h, w, c)

    return apply_fn("channel_shuffle", fn, x)
