"""Convolutions via ``lax.conv_general_dilated`` (MXU path).

Reference: python/paddle/nn/functional/conv.py. Paddle weight layout [O, I/g, *k];
XLA chooses the on-device layout — no im2col/cudnn-algo machinery needed on TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.op_registry import AMP_WHITE, OpDef, apply_fn

_CONV = OpDef("conv", None, amp=AMP_WHITE)


def _tuplize(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(int(x) for x in v)
        if len(v) == 2 * n:  # explicit per-side padding pairs flattened
            return tuple(v)
        return tuple(int(v[0]) for _ in range(n))
    return tuple(int(v) for _ in range(n))


def _padding(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (list, tuple)) and len(padding) and isinstance(padding[0], (list, tuple)):
        # paddle allows [[0,0],[0,0],[ph,ph],[pw,pw]]
        return [tuple(p) for p in padding[-n:]]
    p = _tuplize(padding, n)
    if len(p) == 2 * n:
        return [(int(p[2 * i]), int(p[2 * i + 1])) for i in range(n)]
    return [(int(x), int(x)) for x in p]


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, n, data_format, transpose=False, output_padding=0):
    stride = _tuplize(stride, n)
    dilation = _tuplize(dilation, n)
    pad = _padding(padding, n)
    channels_first = data_format in ("NCHW", "NCL", "NCDHW", "NCW")
    spatial = "DHW"[-n:] if n <= 3 else None
    if channels_first:
        dn_in = "NC" + spatial
        dn_out = "NC" + spatial
    else:
        dn_in = "N" + spatial + "C"
        dn_out = "N" + spatial + "C"
    dn = jax.lax.conv_dimension_numbers((1,) * (n + 2), (1,) * (n + 2), (dn_in, "OI" + spatial, dn_out))

    if not transpose:
        def fn(a, w, *b):
            out = jax.lax.conv_general_dilated(
                a, w, window_strides=stride, padding=pad,
                rhs_dilation=dilation, dimension_numbers=dn, feature_group_count=groups,
                preferred_element_type=jnp.float32 if a.dtype == jnp.float32 else None,
            )
            if b:
                bias_shape = [1] * out.ndim
                bias_shape[1 if channels_first else -1] = -1
                out = out + b[0].reshape(bias_shape)
            return out
    else:
        opad = _tuplize(output_padding, n)

        def fn(a, w, *b):
            # ConvTranspose: paddle weight layout [I, O/g, *k]
            k = w.shape[2:]
            pads = []
            for i in range(n):
                lo, hi = pad[i] if isinstance(pad, list) else (0, 0)
                eff_k = (k[i] - 1) * dilation[i] + 1
                pads.append((eff_k - 1 - lo, eff_k - 1 - hi + opad[i]))
            w_t = jnp.swapaxes(w, 0, 1)  # -> [O/g, I, *k]
            if groups > 1:
                # grouped transpose conv: rearrange to (O, I/g, *k)
                ci = w.shape[0]
                co_g = w.shape[1]
                w_g = w.reshape(groups, ci // groups, co_g, *k)
                w_g = jnp.swapaxes(w_g, 1, 2).reshape(groups * co_g, ci // groups, *k)
                w_t = w_g
            w_t = jnp.flip(w_t, axis=tuple(range(2, 2 + n)))
            out = jax.lax.conv_general_dilated(
                a, w_t, window_strides=(1,) * n, padding=pads,
                lhs_dilation=stride, rhs_dilation=dilation, dimension_numbers=dn,
                feature_group_count=groups,
            )
            if b:
                bias_shape = [1] * out.ndim
                bias_shape[1 if channels_first else -1] = -1
                out = out + b[0].reshape(bias_shape)
            return out

    args = [x, weight] + ([bias] if bias is not None else [])
    return apply_fn("conv%dd%s" % (n, "_transpose" if transpose else ""), fn, *args, _opdef=_CONV)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCL", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 1, data_format)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCDHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 1, data_format, True, output_padding)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2, data_format, True, output_padding)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, output_size=None, data_format="NCDHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3, data_format, True, output_padding)
