"""Activation functionals (reference: python/paddle/nn/functional/activation.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.op_registry import AMP_BLACK, OpDef, apply_fn

_SOFTMAX = OpDef("softmax", None, amp=AMP_BLACK)


def relu(x, name=None):
    return apply_fn("relu", jax.nn.relu, x)


def relu_(x, name=None):
    out = relu(x)
    return x._replace_(out._data, out._node, out._out_idx)


def relu6(x, name=None):
    return apply_fn("relu6", jax.nn.relu6, x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply_fn("leaky_relu", lambda a: jax.nn.leaky_relu(a, negative_slope), x)


def prelu(x, weight, data_format="NCHW", name=None):
    def fn(a, w):
        if w.size == 1:
            return jnp.where(a >= 0, a, w.reshape(()) * a)
        shape = [1] * a.ndim
        ch_axis = 1 if data_format.startswith("NC") else a.ndim - 1
        shape[ch_axis] = w.size
        return jnp.where(a >= 0, a, w.reshape(shape) * a)

    return apply_fn("prelu", fn, x, weight)


def rrelu(x, lower=0.125, upper=0.333, training=False, name=None):
    mid = (lower + upper) / 2
    return apply_fn("rrelu", lambda a: jnp.where(a >= 0, a, mid * a), x)


def elu(x, alpha=1.0, name=None):
    return apply_fn("elu", lambda a: jax.nn.elu(a, alpha), x)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply_fn("selu", lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), x)


def celu(x, alpha=1.0, name=None):
    return apply_fn("celu", lambda a: jax.nn.celu(a, alpha), x)


def gelu(x, approximate=False, name=None):
    return apply_fn("gelu", lambda a: jax.nn.gelu(a, approximate=approximate), x)


def silu(x, name=None):
    return apply_fn("silu", jax.nn.silu, x)


swish = silu


def mish(x, name=None):
    return apply_fn("mish", lambda a: a * jnp.tanh(jax.nn.softplus(a)), x)


def hardswish(x, name=None):
    return apply_fn("hardswish", lambda a: a * jnp.clip(a + 3, 0, 6) / 6, x)


def hardsigmoid(x, slope=1 / 6, offset=0.5, name=None):
    return apply_fn("hardsigmoid", lambda a: jnp.clip(slope * a + offset, 0, 1), x)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply_fn("hardtanh", lambda a: jnp.clip(a, min, max), x)


def hardshrink(x, threshold=0.5, name=None):
    return apply_fn("hardshrink", lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), x)


def softshrink(x, threshold=0.5, name=None):
    return apply_fn(
        "softshrink",
        lambda a: jnp.where(a > threshold, a - threshold, jnp.where(a < -threshold, a + threshold, 0.0)),
        x,
    )


def tanhshrink(x, name=None):
    return apply_fn("tanhshrink", lambda a: a - jnp.tanh(a), x)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply_fn("thresholded_relu", lambda a: jnp.where(a > threshold, a, value), x)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply_fn(
        "softplus",
        lambda a: jnp.where(beta * a > threshold, a, (1.0 / beta) * jnp.log1p(jnp.exp(beta * a))),
        x,
    )


def softsign(x, name=None):
    return apply_fn("softsign", jax.nn.soft_sign, x)


def sigmoid(x, name=None):
    return apply_fn("sigmoid", jax.nn.sigmoid, x)


def logsigmoid(x, name=None):
    return apply_fn("log_sigmoid", jax.nn.log_sigmoid, x)


log_sigmoid = logsigmoid


def tanh(x, name=None):
    return apply_fn("tanh", jnp.tanh, x)


def softmax(x, axis=-1, dtype=None, name=None):
    from ...core import dtype as dtype_mod

    dt = dtype_mod.convert_dtype(dtype)

    def fn(a):
        if dt is not None:
            a = a.astype(dt)
        return jax.nn.softmax(a, axis=int(axis))

    return apply_fn("softmax", fn, x, _opdef=_SOFTMAX)


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ...core import dtype as dtype_mod

    dt = dtype_mod.convert_dtype(dtype)

    def fn(a):
        if dt is not None:
            a = a.astype(dt)
        return jax.nn.log_softmax(a, axis=int(axis))

    return apply_fn("log_softmax", fn, x, _opdef=_SOFTMAX)


def softmax_(x, axis=-1, dtype=None, name=None):
    out = softmax(x, axis, dtype)
    return x._replace_(out._data, out._node, out._out_idx)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...framework.random import next_key

    def fn(a):
        key = next_key()  # inside the kernel: fresh under static rng_guard
        g = jax.random.gumbel(key, a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            onehot = jax.nn.one_hot(jnp.argmax(y, axis=axis), y.shape[axis], axis=axis, dtype=y.dtype)
            y = onehot + y - jax.lax.stop_gradient(y)
        return y

    return apply_fn("gumbel_softmax", fn, x)


def maxout(x, groups, axis=1, name=None):
    def fn(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(new_shape), axis=ax + 1)

    return apply_fn("maxout", fn, x)


def glu(x, axis=-1, name=None):
    def fn(a):
        a1, a2 = jnp.split(a, 2, axis=axis)
        return a1 * jax.nn.sigmoid(a2)

    return apply_fn("glu", fn, x)
