"""Pooling functionals via ``lax.reduce_window`` (reference: nn/functional/pooling.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.op_registry import apply_fn


def _tuplize(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v[:n]) if len(v) >= n else tuple(int(v[0]) for _ in range(n))
    return tuple(int(v) for _ in range(n))


def _pool(x, kernel, stride, padding, n, reducer, init, channels_first, count_include_pad=True, ceil_mode=False, is_avg=False):
    kernel = _tuplize(kernel, n)
    stride = _tuplize(stride if stride is not None else kernel, n)
    pad = _tuplize(padding, n)

    def fn(a):
        if channels_first:
            dims = (1, 1) + kernel
            strides = (1, 1) + stride
            pads = ((0, 0), (0, 0)) + tuple((p, p) for p in pad)
        else:
            dims = (1,) + kernel + (1,)
            strides = (1,) + stride + (1,)
            pads = ((0, 0),) + tuple((p, p) for p in pad) + ((0, 0),)
        out = jax.lax.reduce_window(a, init(a.dtype), reducer, dims, strides, pads)
        if is_avg:
            if count_include_pad:
                denom = float(np.prod(kernel))
                out = out / denom
            else:
                ones = jnp.ones_like(a)
                cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims, strides, pads)
                out = out / cnt
        return out

    return apply_fn("pool", fn, x)


def _max_pool(x, kernel_size, stride, padding, n, return_mask, ceil_mode, data_format):
    if return_mask:
        if not data_format.startswith("NC"):
            raise NotImplementedError("return_mask requires channels-first layout")
        from .extras import _pool_with_mask

        return _pool_with_mask(x, kernel_size, stride, padding, n,
                               ceil_mode=ceil_mode)
    return _pool(x, kernel_size, stride, padding, n, jax.lax.max,
                 lambda dt: -jnp.inf if jnp.issubdtype(dt, jnp.floating) else int(jnp.iinfo(dt).min),
                 data_format.startswith("NC"), ceil_mode=ceil_mode)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCL", name=None):
    return _max_pool(x, kernel_size, stride, padding, 1, return_mask, ceil_mode, data_format)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCHW", name=None):
    return _max_pool(x, kernel_size, stride, padding, 2, return_mask, ceil_mode, data_format)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, data_format="NCDHW", name=None):
    return _max_pool(x, kernel_size, stride, padding, 3, return_mask, ceil_mode, data_format)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, jax.lax.add, lambda dt: 0.0 if jnp.issubdtype(dt, jnp.floating) else 0, data_format.startswith("NC"), count_include_pad=not exclusive, ceil_mode=ceil_mode, is_avg=True)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, jax.lax.add, lambda dt: 0.0 if jnp.issubdtype(dt, jnp.floating) else 0, data_format.startswith("NC"), count_include_pad=not exclusive, ceil_mode=ceil_mode, is_avg=True)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, jax.lax.add, lambda dt: 0.0 if jnp.issubdtype(dt, jnp.floating) else 0, data_format.startswith("NC"), count_include_pad=not exclusive, ceil_mode=ceil_mode, is_avg=True)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "avg", True)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, "avg", data_format.startswith("NC"))


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, "avg", data_format.startswith("NC"))


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 1, "max", True)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 2, "max", True)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 3, "max", True)


def _adaptive(x, output_size, n, kind, channels_first):
    out_sz = _tuplize(output_size, n)

    def fn(a):
        spatial = a.shape[2:] if channels_first else a.shape[1:-1]
        out = a
        # pool each spatial dim independently with variable windows
        for d in range(n):
            in_s, out_s = spatial[d], out_sz[d]
            axis = (2 + d) if channels_first else (1 + d)
            if out_s == in_s:
                continue
            starts = [int(np.floor(i * in_s / out_s)) for i in range(out_s)]
            ends = [int(np.ceil((i + 1) * in_s / out_s)) for i in range(out_s)]
            segs = []
            for s, e in zip(starts, ends):
                sl = [slice(None)] * out.ndim
                sl[axis] = slice(s, e)
                seg = out[tuple(sl)]
                seg = seg.mean(axis=axis, keepdims=True) if kind == "avg" else seg.max(axis=axis, keepdims=True)
                segs.append(seg)
            out = jnp.concatenate(segs, axis=axis)
        return out

    return apply_fn("adaptive_pool", fn, x)


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False, data_format="NCL", name=None):
    from .activation import relu

    p = float(norm_type)

    def fn(a):
        k = _tuplize(kernel_size, 1)
        s = _tuplize(stride if stride is not None else kernel_size, 1)
        powed = jnp.abs(a) ** p
        summed = jax.lax.reduce_window(powed, 0.0, jax.lax.add, (1, 1) + k, (1, 1) + s, ((0, 0), (0, 0), (padding, padding)))
        return summed ** (1.0 / p)

    return apply_fn("lp_pool1d", fn, x)


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0, ceil_mode=False, data_format="NCHW", name=None):
    p = float(norm_type)

    def fn(a):
        k = _tuplize(kernel_size, 2)
        s = _tuplize(stride if stride is not None else kernel_size, 2)
        pd = _tuplize(padding, 2)
        powed = jnp.abs(a) ** p
        summed = jax.lax.reduce_window(powed, 0.0, jax.lax.add, (1, 1) + k, (1, 1) + s, ((0, 0), (0, 0)) + tuple((q, q) for q in pd))
        return summed ** (1.0 / p)

    return apply_fn("lp_pool2d", fn, x)
