"""Attention functionals (reference: python/paddle/nn/functional/flash_attention.py).

Layout follows the reference: q/k/v are [batch, seq, num_heads, head_dim]
(flash_attention.py:195). On TPU the hot path is a Pallas flash-attention kernel
(paddle_tpu/ops/flash_attention.py); elsewhere (CPU tests, odd shapes) an XLA
composite attention is used — still fused well by XLA, just not block-streamed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ... import flags
from ...core.op_registry import apply_fn
from ...framework.random import next_key


def _xla_attention(q, k, v, bias=None, causal=False, scale=None, dropout=0.0, dropout_key=None):
    # q,k,v: [b, s, h, d] -> compute in [b, h, s, d]
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    d = q.shape[-1]
    s = scale if scale is not None else d ** -0.5
    # GQA: broadcast kv heads if fewer than q heads
    if kh.shape[1] != qh.shape[1]:
        rep = qh.shape[1] // kh.shape[1]
        kh = jnp.repeat(kh, rep, axis=1)
        vh = jnp.repeat(vh, rep, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * s
    logits = logits.astype(jnp.float32)
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(mask, logits, jnp.float32(-1e9))
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout), jnp.zeros_like(probs))
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2)


def _attention_impl(q, k, v, bias, causal, scale, dropout, dropout_key):
    use_pallas = flags.get_flag("use_pallas_attention") and bias is None and dropout == 0.0
    if use_pallas:
        try:
            from ...ops.flash_attention import flash_attention_fwd

            return flash_attention_fwd(q, k, v, causal=causal, scale=scale)
        except Exception:
            pass
    return _xla_attention(q, k, v, bias, causal, scale, dropout, dropout_key)


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None):
    """Reference: nn/functional/flash_attention.py:976."""
    dk = next_key() if (dropout_p > 0.0 and training) else None
    drop = dropout_p if training else 0.0

    def fn(q, kk, vv, *mask):
        b = mask[0] if mask else None
        if b is not None and b.dtype == jnp.bool_:
            b = jnp.where(b, 0.0, -1e9).astype(jnp.float32)
        return _attention_impl(q, kk, vv, b, is_causal, None, drop, dk)

    args = [query, key, value] + ([attn_mask] if attn_mask is not None else [])
    return apply_fn("scaled_dot_product_attention", fn, *args)


def flash_attention(query, key, value, dropout=0.0, causal=False, return_softmax=False,
                    fixed_seed_offset=None, rng_name="", training=True, name=None):
    """Reference: nn/functional/flash_attention.py:195. Returns (out, softmax|None)."""
    out = scaled_dot_product_attention(query, key, value, None, dropout, causal, training)
    return out, None


def flashmask_attention(query, key, value, startend_row_indices=None, dropout=0.0,
                        causal=False, window_size=None, return_softmax_lse=False,
                        return_seed_offset=False, fixed_seed_offset=None, rng_name="",
                        training=True, name=None):
    """Sparse-mask attention (reference :1098 over the flashmask CUDA
    kernels). The LT start/end encodings ([b, hm, kv_len, {1,2}]) stream
    through the in-repo Pallas flash kernel as per-column row bounds
    (ops/flash_attention.flash_attention_rowmask — fwd AND bwd); the 4-index
    bidirectional encodings fall back to a dense additive bias."""
    from ...core.tensor import Tensor, unwrap

    if startend_row_indices is not None:
        idx = unwrap(startend_row_indices)  # [b, hm, kv_len, {1,2,4}]
        b, hm, kv_len, nidx = idx.shape
        q_len = query.shape[1]
        if causal and nidx <= 2 and dropout == 0.0:
            # kernel path (causal LT encodings): per kv column, q rows in
            # [LT_start, LT_end) are masked (LT_end = ∞ for the 1-index form)
            start = idx[..., 0]
            end = (idx[..., 1] if nidx >= 2
                   else jnp.full_like(start, q_len + kv_len))
            from ...core.op_registry import apply_fn
            from ...ops.flash_attention import flash_attention_rowmask

            def fn(q, k, v, st, en):
                return flash_attention_rowmask(q, k, v, st, en, causal, None)

            return apply_fn("flashmask_attention", fn, query, key, value,
                            Tensor(start), Tensor(end))
        # dense additive-bias path:
        #   causal 4-index  [LTS, LTE, UTS, UTE]: two masked bands
        #   non-causal 2-index [LTS, UTE]: masked rows >= LTS OR rows < UTE
        #   non-causal 4-index [LTS, LTE, UTS, UTE]: two masked bands
        rows = jnp.arange(q_len)[None, None, :, None]
        lts = idx[..., 0][:, :, None, :]
        if causal:
            mask = rows >= lts
            if nidx >= 2:
                lte = idx[..., 1][:, :, None, :]
                mask = mask & (rows < lte)
        elif nidx == 2:
            ute = idx[..., 1][:, :, None, :]
            mask = (rows >= lts) | (rows < ute)
        else:
            lte = idx[..., 1][:, :, None, :]
            uts = idx[..., 2][:, :, None, :]
            ute = idx[..., 3][:, :, None, :]
            mask = ((rows >= lts) & (rows < lte)) | \
                   ((rows >= uts) & (rows < ute))
        bias = jnp.where(mask, jnp.float32(-1e9), 0.0)
        return scaled_dot_product_attention(query, key, value, Tensor(bias),
                                            dropout, causal, training)
    return scaled_dot_product_attention(query, key, value, None, dropout,
                                        causal, training)


def sdp_kernel(*args, **kwargs):
    import contextlib

    return contextlib.nullcontext()
