"""Attention functionals (reference: python/paddle/nn/functional/flash_attention.py).

Layout follows the reference: q/k/v are [batch, seq, num_heads, head_dim]
(flash_attention.py:195). On TPU the hot path is a Pallas flash-attention kernel
(paddle_tpu/ops/flash_attention.py); elsewhere (CPU tests, odd shapes) an XLA
composite attention is used — still fused well by XLA, just not block-streamed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ... import flags
from ...core.op_registry import apply_fn
from ...framework.random import next_key


def _xla_attention(q, k, v, bias=None, causal=False, scale=None, dropout=0.0, dropout_key=None):
    # q,k,v: [b, s, h, d] -> compute in [b, h, s, d]
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    d = q.shape[-1]
    s = scale if scale is not None else d ** -0.5
    # GQA: broadcast kv heads if fewer than q heads
    if kh.shape[1] != qh.shape[1]:
        rep = qh.shape[1] // kh.shape[1]
        kh = jnp.repeat(kh, rep, axis=1)
        vh = jnp.repeat(vh, rep, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * s
    logits = logits.astype(jnp.float32)
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if causal:
        ql, kl = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((ql, kl), bool), k=kl - ql)
        logits = jnp.where(mask, logits, jnp.float32(-1e9))
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout), jnp.zeros_like(probs))
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2)


def _attention_impl(q, k, v, bias, causal, scale, dropout, dropout_key):
    use_pallas = flags.get_flag("use_pallas_attention") and bias is None and dropout == 0.0
    if use_pallas:
        try:
            from ...ops.flash_attention import flash_attention_fwd

            return flash_attention_fwd(q, k, v, causal=causal, scale=scale)
        except Exception:
            pass
    return _xla_attention(q, k, v, bias, causal, scale, dropout, dropout_key)


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None):
    """Reference: nn/functional/flash_attention.py:976."""
    dk = next_key() if (dropout_p > 0.0 and training) else None
    drop = dropout_p if training else 0.0

    def fn(q, kk, vv, *mask):
        b = mask[0] if mask else None
        if b is not None and b.dtype == jnp.bool_:
            b = jnp.where(b, 0.0, -1e9).astype(jnp.float32)
        return _attention_impl(q, kk, vv, b, is_causal, None, drop, dk)

    args = [query, key, value] + ([attn_mask] if attn_mask is not None else [])
    return apply_fn("scaled_dot_product_attention", fn, *args)


def flash_attention(query, key, value, dropout=0.0, causal=False, return_softmax=False,
                    fixed_seed_offset=None, rng_name="", training=True, name=None):
    """Reference: nn/functional/flash_attention.py:195. Returns (out, softmax|None)."""
    out = scaled_dot_product_attention(query, key, value, None, dropout, causal, training)
    return out, None


def flashmask_attention(query, key, value, startend_row_indices=None, dropout=0.0,
                        causal=False, window_size=None, return_softmax_lse=False,
                        return_seed_offset=False, fixed_seed_offset=None, rng_name="",
                        training=True, name=None):
    """Sparse-mask attention (reference :1098). Round-1: dense-mask materialization."""
    bias = None
    if startend_row_indices is not None:
        # Build an additive bias from start/end row indices: masked where kv row >= start.
        import numpy as np

        from ...core.tensor import unwrap

        idx = unwrap(startend_row_indices)  # [b, kv_heads, kv_len, {1,2,4}]
        b, h, kv_len, nidx = idx.shape
        q_len = query.shape[1]
        rows = jnp.arange(q_len)[None, None, :, None]
        if causal:
            start = idx[..., 0][:, :, None, :]  # [b,h,1,kv]
            mask = rows >= start
            if nidx >= 2:
                end = idx[..., 1][:, :, None, :]
                mask = mask & (rows < end)
            bias = jnp.where(mask, jnp.float32(-1e9), 0.0)
        else:
            start = idx[..., 0][:, :, None, :]
            mask = rows >= start
            bias = jnp.where(mask, jnp.float32(-1e9), 0.0)
    from ...core.tensor import Tensor

    out = scaled_dot_product_attention(query, key, value,
                                       None if bias is None else Tensor(bias),
                                       dropout, causal, training)
    return out


def sdp_kernel(*args, **kwargs):
    import contextlib

    return contextlib.nullcontext()
