"""Normalization functionals (reference: python/paddle/nn/functional/norm.py).

AMP-black ops: statistics computed in fp32 regardless of input dtype, matching the
reference's norm kernels; XLA fuses the whole normalize+affine chain on TPU.
"""

from __future__ import annotations

import jax.numpy as jnp

from ...core.op_registry import apply_fn


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    nd = len(tuple(normalized_shape))

    def fn(a, *wb):
        axes = tuple(range(a.ndim - nd, a.ndim))
        dt = a.dtype
        af = a.astype(jnp.float32)
        mean = af.mean(axis=axes, keepdims=True)
        var = af.var(axis=axes, keepdims=True)
        out = (af - mean) / jnp.sqrt(var + epsilon)
        out = out.astype(dt)
        i = 0
        if weight is not None:
            out = out * wb[i]
            i += 1
        if bias is not None:
            out = out + wb[i]
        return out

    args = [x] + [w for w in (weight, bias) if w is not None]
    return apply_fn("layer_norm", fn, *args)


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (the reference exposes it as incubate fused_rms_norm)."""

    def fn(a, *w):
        dt = a.dtype
        af = a.astype(jnp.float32)
        ms = jnp.mean(jnp.square(af), axis=-1, keepdims=True)
        out = (af * jnp.reciprocal(jnp.sqrt(ms + epsilon))).astype(dt)
        if w:
            out = out * w[0]
        return out

    args = [x] + ([weight] if weight is not None else [])
    return apply_fn("rms_norm", fn, *args)


def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW", use_global_stats=None, name=None):
    ch_axis = 1 if data_format.startswith("NC") else -1
    use_batch_stats = training and not use_global_stats

    def fn(a, rm, rv, *wb):
        shape = [1] * a.ndim
        shape[ch_axis] = a.shape[ch_axis]
        axes = tuple(i for i in range(a.ndim) if i != (ch_axis % a.ndim))
        if use_batch_stats:
            mean = a.astype(jnp.float32).mean(axis=axes)
            var = a.astype(jnp.float32).var(axis=axes)
        else:
            mean, var = rm, rv
        out = (a - mean.reshape(shape).astype(a.dtype)) * (
            1.0 / jnp.sqrt(var.reshape(shape).astype(jnp.float32) + epsilon)
        ).astype(a.dtype)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        if not use_batch_stats:
            return out
        n = 1
        for ax in axes:
            n *= a.shape[ax]
        unbiased = var * n / max(n - 1, 1)
        new_mean = momentum * rm + (1 - momentum) * mean.astype(rm.dtype)
        new_var = momentum * rv + (1 - momentum) * unbiased.astype(rv.dtype)
        return out, new_mean, new_var

    args = [x, running_mean, running_var] + [w for w in (weight, bias) if w is not None]
    res = apply_fn("batch_norm", fn, *args)
    if not use_batch_stats:
        return res

    out, new_mean_t, new_var_t = res
    # update running stats (mirrors the reference's in-kernel update). Under a
    # trace (jitted train step) the update is staged on the buffer as
    # `_pending_update`; the functionalized step (hapi/model.py) threads it
    # through as carried state.
    import jax

    if isinstance(new_mean_t._data, jax.core.Tracer):
        running_mean._pending_update = new_mean_t._data
        running_var._pending_update = new_var_t._data
    else:
        running_mean.set_value(new_mean_t._data)
        running_var.set_value(new_var_t._data)
    return out


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None, use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW", name=None):
    def fn(a, *wb):
        axes = tuple(range(2, a.ndim))
        mean = a.mean(axis=axes, keepdims=True)
        var = a.var(axis=axes, keepdims=True)
        out = (a - mean) / jnp.sqrt(var + eps)
        shape = [1, a.shape[1]] + [1] * (a.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    args = [x] + [w for w in (weight, bias) if w is not None]
    return apply_fn("instance_norm", fn, *args)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None, data_format="NCHW", name=None):
    def fn(a, *wb):
        if data_format == "NLC" or not data_format.startswith("NC"):
            a_t = jnp.moveaxis(a, -1, 1)
        else:
            a_t = a
        n, c = a_t.shape[0], a_t.shape[1]
        g = num_groups
        grouped = a_t.reshape(n, g, c // g, *a_t.shape[2:])
        axes = tuple(range(2, grouped.ndim))
        mean = grouped.mean(axis=axes, keepdims=True)
        var = grouped.var(axis=axes, keepdims=True)
        out = ((grouped - mean) / jnp.sqrt(var + epsilon)).reshape(a_t.shape)
        shape = [1, c] + [1] * (a_t.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        if data_format == "NLC" or not data_format.startswith("NC"):
            out = jnp.moveaxis(out, 1, -1)
        return out

    args = [x] + [w for w in (weight, bias) if w is not None]
    return apply_fn("group_norm", fn, *args)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    def fn(a):
        sq = jnp.square(a)
        c = a.shape[1]
        half = size // 2
        padded = jnp.pad(sq, ((0, 0), (half, size - 1 - half)) + ((0, 0),) * (a.ndim - 2))
        acc = jnp.zeros_like(a)
        for i in range(size):
            acc = acc + padded[:, i : i + c]
        return a / (k + alpha * acc) ** beta

    return apply_fn("local_response_norm", fn, x)


def spectral_norm(x, weight_u, weight_v, dim=0, power_iters=1, eps=1e-12, name=None):
    def fn(w, u, v):
        w_mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        for _ in range(power_iters):
            v = w_mat.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = w_mat @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ w_mat @ v
        return w / sigma

    return apply_fn("spectral_norm", fn, x, weight_u, weight_v)
