"""Parameter initializers (reference: python/paddle/nn/initializer/)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtype_mod
from ..framework.random import next_key


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels: paddle uses [out_c, in_c, *spatial]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(tuple(shape), self.value, dtype_mod.convert_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        dt = dtype_mod.convert_dtype(dtype)
        return jax.random.normal(next_key(), tuple(shape), dt) * self.std + self.mean


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        dt = dtype_mod.convert_dtype(dtype)
        return jax.random.truncated_normal(next_key(), self.a, self.b, tuple(shape), dt) * self.std + self.mean


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        dt = dtype_mod.convert_dtype(dtype)
        return jax.random.uniform(next_key(), tuple(shape), dt, self.low, self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return jax.random.normal(next_key(), tuple(shape), dtype_mod.convert_dtype(dtype)) * std


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(next_key(), tuple(shape), dtype_mod.convert_dtype(dtype), -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope = fan_in, negative_slope

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        std = math.sqrt(2.0 / ((1 + self.negative_slope**2) * fi))
        return jax.random.normal(next_key(), tuple(shape), dtype_mod.convert_dtype(dtype)) * std


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in, self.negative_slope = fan_in, negative_slope

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        limit = math.sqrt(6.0 / ((1 + self.negative_slope**2) * fi))
        return jax.random.uniform(next_key(), tuple(shape), dtype_mod.convert_dtype(dtype), -limit, limit)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        from ..core.tensor import unwrap

        arr = jnp.asarray(unwrap(self.value), dtype_mod.convert_dtype(dtype))
        return arr.reshape(tuple(shape))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        return jax.nn.initializers.orthogonal(self.gain)(next_key(), tuple(shape), dtype_mod.convert_dtype(dtype))


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        arr = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        mid = tuple(s // 2 for s in shape[2:])
        for i in range(min(oc, ic * self.groups)):
            arr[(i, i % ic) + mid] = 1.0
        return jnp.asarray(arr, dtype_mod.convert_dtype(dtype))


def calculate_gain(nonlinearity, param=None):
    if nonlinearity == "tanh":
        return 5.0 / 3
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = param if param is not None else 0.01
        return math.sqrt(2.0 / (1 + a**2))
    if nonlinearity == "selu":
        return 3.0 / 4
    return 1.0


# modern aliases used by ParamAttr(initializer=...)
constant = Constant
normal = Normal
uniform = Uniform
