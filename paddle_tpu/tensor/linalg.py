"""Linear algebra ops (reference: python/paddle/tensor/linalg.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.op_registry import AMP_WHITE, OpDef, apply_fn
from ..core.tensor import Tensor, unwrap

_MM = OpDef("matmul", None, amp=AMP_WHITE)


def dot(x, y, name=None):
    return apply_fn("dot", lambda a, b: jnp.sum(a * b, axis=-1), x, y)


def bmm(x, y, name=None):
    return apply_fn("bmm", jnp.matmul, x, y, _opdef=_MM)


def mv(x, vec, name=None):
    return apply_fn("mv", jnp.matmul, x, vec, _opdef=_MM)


def norm(x, p=None, axis=None, keepdim=False, name=None):
    def fn(a):
        if p is None or p == "fro":
            if axis is None:
                return jnp.sqrt(jnp.sum(jnp.square(a)))
            return jnp.linalg.norm(a, ord=None, axis=tuple(axis) if isinstance(axis, (list, tuple)) else axis, keepdims=keepdim)
        if p == "inf" or p == float("inf"):
            ordv = jnp.inf
        elif p == "-inf" or p == -float("inf"):
            ordv = -jnp.inf
        else:
            ordv = p
        if axis is None:
            return jnp.linalg.norm(a.reshape(-1), ord=ordv, keepdims=keepdim)
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        return jnp.linalg.norm(a, ord=ordv, axis=ax, keepdims=keepdim)

    return apply_fn("norm", fn, x)


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    def fn(a):
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if ax is None:
            a = a.reshape(-1)
            ax = 0
        return jnp.linalg.norm(a, ord=p, axis=ax, keepdims=keepdim)

    return apply_fn("vector_norm", fn, x)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    return apply_fn("matrix_norm", lambda a: jnp.linalg.norm(a, ord=p if p != "fro" else None, axis=tuple(axis), keepdims=keepdim), x)


def cond(x, p=None, name=None):
    return apply_fn("cond", lambda a: jnp.linalg.cond(a, p=p), x)


def cholesky(x, upper=False, name=None):
    def fn(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2).conj() if upper else L

    return apply_fn("cholesky", fn, x)


def cholesky_solve(x, y, upper=False, name=None):
    def fn(b, L):
        return jax.scipy.linalg.cho_solve((L, not upper), b)

    return apply_fn("cholesky_solve", fn, x, y)


def qr(x, mode="reduced", name=None):
    out = apply_fn("qr", lambda a: tuple(jnp.linalg.qr(a, mode=mode)) if mode != "r" else (jnp.linalg.qr(a, mode="r"),), x)
    if mode == "r":
        return out[0]
    return out


def svd(x, full_matrices=False, name=None):
    def fn(a):
        u, s, vh = jnp.linalg.svd(a, full_matrices=full_matrices)
        return u, s, jnp.swapaxes(vh, -1, -2).conj()

    return apply_fn("svd", fn, x)


def svdvals(x, name=None):
    return apply_fn("svdvals", lambda a: jnp.linalg.svd(a, compute_uv=False), x)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    a = unwrap(x)
    if center:
        a = a - a.mean(axis=-2, keepdims=True)
    u, s, vh = jnp.linalg.svd(a, full_matrices=False)
    k = q if q is not None else min(6, *a.shape[-2:])
    return Tensor(u[..., :k]), Tensor(s[..., :k]), Tensor(jnp.swapaxes(vh, -1, -2)[..., :k])


def inv(x, name=None):
    return apply_fn("inv", jnp.linalg.inv, x)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply_fn("pinv", lambda a: jnp.linalg.pinv(a, rcond=rcond, hermitian=hermitian), x)


def det(x, name=None):
    return apply_fn("det", jnp.linalg.det, x)


def slogdet(x, name=None):
    def fn(a):
        sign, logdet = jnp.linalg.slogdet(a)
        return jnp.stack([sign, logdet], axis=0)

    return apply_fn("slogdet", fn, x)


def solve(x, y, name=None):
    def fn(a, b):
        if b.ndim == a.ndim - 1:
            return jnp.linalg.solve(a, b[..., None])[..., 0]
        return jnp.linalg.solve(a, b)

    return apply_fn("solve", fn, x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def fn(a, b):
        return jax.scipy.linalg.solve_triangular(a, b, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular)

    return apply_fn("triangular_solve", fn, x, y)


def lstsq(x, y, rcond=None, driver=None, name=None):
    def fn(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv

    out = apply_fn("lstsq", fn, x, y)
    return out


def lu(x, pivot=True, get_infos=False, name=None):
    def fn(a):
        lu_mat, piv = jax.scipy.linalg.lu_factor(a)
        return lu_mat, piv + 1  # paddle returns 1-based pivots

    out = apply_fn("lu", fn, x)
    if get_infos:
        return out[0], out[1], Tensor(jnp.zeros((), jnp.int32))
    return out


def matrix_power(x, n, name=None):
    return apply_fn("matrix_power", lambda a: jnp.linalg.matrix_power(a, n), x)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply_fn("matrix_rank", lambda a: jnp.linalg.matrix_rank(a, tol=unwrap(tol)), x)


def eig(x, name=None):
    import numpy as np

    w, v = np.linalg.eig(np.asarray(unwrap(x)))
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigh(x, UPLO="L", name=None):
    return apply_fn("eigh", lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)), x)


def eigvals(x, name=None):
    import numpy as np

    return Tensor(jnp.asarray(np.linalg.eigvals(np.asarray(unwrap(x)))))


def eigvalsh(x, UPLO="L", name=None):
    return apply_fn("eigvalsh", lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), x)


def cross(x, y, axis=9, name=None):
    def fn(a, b):
        ax = axis
        if ax == 9:
            for i, s in enumerate(a.shape):
                if s == 3:
                    ax = i
                    break
        return jnp.cross(a, b, axis=ax)

    return apply_fn("cross", fn, x, y)


def householder_product(x, tau, name=None):
    def fn(a, t):
        m, n = a.shape[-2], a.shape[-1]
        eye = jnp.eye(m, dtype=a.dtype)
        q = jnp.broadcast_to(eye, a.shape[:-2] + (m, m)).copy() if a.ndim > 2 else eye
        for i in range(n):
            v = jnp.concatenate([jnp.zeros(a.shape[:-2] + (i,), a.dtype),
                                 jnp.ones(a.shape[:-2] + (1,), a.dtype),
                                 a[..., i + 1:, i]], axis=-1)
            h = jnp.eye(m, dtype=a.dtype) - t[..., i, None, None] * v[..., :, None] * v[..., None, :]
            q = q @ h
        return q[..., :n]

    return apply_fn("householder_product", fn, x, tau)


def corrcoef(x, rowvar=True, name=None):
    return apply_fn("corrcoef", lambda a: jnp.corrcoef(a, rowvar=rowvar), x)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply_fn("cov", lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0), x)


def multi_dot(x, name=None):
    return apply_fn("multi_dot", lambda *xs: jnp.linalg.multi_dot(xs), *x, _opdef=_MM)


def matrix_exp(x, name=None):
    """Matrix exponential (reference: tensor/linalg.py matrix_exp)."""
    import jax.scipy.linalg as jsl

    return apply_fn("matrix_exp", lambda a: jsl.expm(a), x)


def fp8_fp8_half_gemm_fused(x, y, bias=None, transpose_x=False,
                            transpose_y=False, scale=1.0,
                            output_dtype="float16", activation_type="identity",
                            name=None):
    """fp8 x fp8 -> half GEMM (reference: incubate cublaslt fp8 gemm).
    TPU-native: cast through float8_e4m3fn and let XLA pick the low-precision
    dot; accumulation in fp32, output in half precision."""
    import jax
    import jax.numpy as jnp
    from ..core import dtype as dtype_mod

    out_dt = dtype_mod.convert_dtype(output_dtype)

    def fn(a, b, *bias_arr):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        a8 = a.astype(jnp.float8_e4m3fn)
        b8 = b.astype(jnp.float8_e4m3fn)
        acc = jax.lax.dot_general(
            a8, b8, (((a8.ndim - 1,), (b8.ndim - 2,)), ((), ())),
            preferred_element_type=jnp.float32)
        out = acc * scale
        if bias_arr:
            out = out + bias_arr[0].astype(jnp.float32)
        if activation_type == "gelu":
            out = jax.nn.gelu(out)
        elif activation_type == "relu":
            out = jax.nn.relu(out)
        return out.astype(out_dt)

    if bias is not None:
        return apply_fn("fp8_gemm_fused", fn, x, y, bias)
    return apply_fn("fp8_gemm_fused", fn, x, y)


# re-exports so paddle.linalg.* matches the reference namespace
from .extras import cholesky_inverse, lu_unpack, ormqr, svd_lowrank  # noqa: E402,F401
