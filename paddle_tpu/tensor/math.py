"""Elementwise & reduction math ops (reference: python/paddle/tensor/math.py, 164 defs).

Each op is a pure jnp/lax function dispatched through the registry — eager mode gets
tape recording, jit mode gets inlined into the jaxpr, and XLA fuses the elementwise
chains into single TPU kernels (no hand-written fused kernels needed at this level).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dtype as dtype_mod
from ..core.op_registry import AMP_BLACK, AMP_WHITE, apply_fn
from ..core.tensor import Tensor, unwrap, wrap


def _u(x):
    return unwrap(x)


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        a = axis.tolist()
        return tuple(a) if isinstance(a, list) else int(a)
    if isinstance(axis, (list, tuple)):
        return tuple(int(_u(a)) for a in axis)
    return int(axis)


# ---------- binary elementwise ----------

def add(x, y, name=None):
    return apply_fn("add", jnp.add, x, y)


def subtract(x, y, name=None):
    return apply_fn("subtract", jnp.subtract, x, y)


def multiply(x, y, name=None):
    return apply_fn("multiply", jnp.multiply, x, y)


def divide(x, y, name=None):
    return apply_fn("divide", jnp.true_divide, x, y)


def floor_divide(x, y, name=None):
    return apply_fn("floor_divide", jnp.floor_divide, x, y)


def mod(x, y, name=None):
    return apply_fn("mod", jnp.mod, x, y)


remainder = mod
floor_mod = mod


def pow(x, y, name=None):
    return apply_fn("pow", jnp.power, x, y)


def maximum(x, y, name=None):
    return apply_fn("maximum", jnp.maximum, x, y)


def minimum(x, y, name=None):
    return apply_fn("minimum", jnp.minimum, x, y)


def fmax(x, y, name=None):
    return apply_fn("fmax", jnp.fmax, x, y)


def fmin(x, y, name=None):
    return apply_fn("fmin", jnp.fmin, x, y)


def atan2(x, y, name=None):
    return apply_fn("atan2", jnp.arctan2, x, y)


def hypot(x, y, name=None):
    return apply_fn("hypot", jnp.hypot, x, y)


def copysign(x, y, name=None):
    return apply_fn("copysign", jnp.copysign, x, y)


def nextafter(x, y, name=None):
    return apply_fn("nextafter", jnp.nextafter, x, y)


def heaviside(x, y, name=None):
    return apply_fn("heaviside", jnp.heaviside, x, y)


def gcd(x, y, name=None):
    return apply_fn("gcd", jnp.gcd, x, y)


def lcm(x, y, name=None):
    return apply_fn("lcm", jnp.lcm, x, y)


def logaddexp(x, y, name=None):
    return apply_fn("logaddexp", jnp.logaddexp, x, y)


# ---------- unary elementwise ----------

def _unary(name, fn, amp=None):
    op_name = name

    def op(x, name=None):
        return apply_fn(op_name, fn, x)

    op.__name__ = op_name
    return op


exp = _unary("exp", jnp.exp)
expm1 = _unary("expm1", jnp.expm1)
log = _unary("log", jnp.log)
log2 = _unary("log2", jnp.log2)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
sqrt = _unary("sqrt", jnp.sqrt)
rsqrt = _unary("rsqrt", jax.lax.rsqrt)
abs = _unary("abs", jnp.abs)
sign = _unary("sign", jnp.sign)
neg = _unary("neg", jnp.negative)
negative = neg
sin = _unary("sin", jnp.sin)
cos = _unary("cos", jnp.cos)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
acos = _unary("acos", jnp.arccos)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
cosh = _unary("cosh", jnp.cosh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
acosh = _unary("acosh", jnp.arccosh)
atanh = _unary("atanh", jnp.arctanh)
ceil = _unary("ceil", jnp.ceil)
floor = _unary("floor", jnp.floor)
round = _unary("round", jnp.round)
trunc = _unary("trunc", jnp.trunc)
frac = _unary("frac", lambda a: a - jnp.trunc(a))
reciprocal = _unary("reciprocal", jnp.reciprocal)
square = _unary("square", jnp.square)
sigmoid = _unary("sigmoid", jax.nn.sigmoid)
erf = _unary("erf", jax.scipy.special.erf)
erfinv = _unary("erfinv", jax.scipy.special.erfinv)
lgamma = _unary("lgamma", jax.scipy.special.gammaln)
digamma = _unary("digamma", jax.scipy.special.digamma)
i0 = _unary("i0", lambda a: jax.scipy.special.i0(a))
i1 = _unary("i1", lambda a: jax.scipy.special.i1(a))
angle = _unary("angle", jnp.angle)
conj = _unary("conj", jnp.conj)
real = _unary("real", jnp.real)
imag = _unary("imag", jnp.imag)
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)
exponent = None  # not in paddle


def isnan(x, name=None):
    return apply_fn("isnan", jnp.isnan, x)


def isinf(x, name=None):
    return apply_fn("isinf", jnp.isinf, x)


def isfinite(x, name=None):
    return apply_fn("isfinite", jnp.isfinite, x)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply_fn("nan_to_num", lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf), x)


def clip(x, min=None, max=None, name=None):
    return apply_fn("clip", lambda a: jnp.clip(a, _u(min), _u(max)), x)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s, b = _u(scale), _u(bias)

    def fn(a):
        out = a * s + b if bias_after_scale else (a + b) * s
        return out

    return apply_fn("scale", fn, x)


def increment(x, value=1.0, name=None):
    x._replace_(x._data + value, x._node, x._out_idx)
    return x


def lerp(x, y, weight, name=None):
    if isinstance(weight, (int, float)):
        return apply_fn("lerp", lambda a, b: a + weight * (b - a), x, y)
    return apply_fn("lerp", lambda a, b, w: a + w * (b - a), x, y, weight)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply_fn("stanh", lambda a: scale_b * jnp.tanh(scale_a * a), x)


def multiplex(inputs, index, name=None):
    def fn(idx, *xs):
        stacked = jnp.stack(xs, axis=0)
        return jnp.take_along_axis(stacked, idx.reshape(1, -1, *([1] * (stacked.ndim - 2))), axis=0)[0]

    return apply_fn("multiplex", fn, index, *inputs)


# ---------- matmul family (MXU ops — AMP white) ----------

def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)

    return apply_fn("matmul", fn, x, y, _opdef=_MATMUL_DEF)


from ..core.op_registry import OpDef  # noqa: E402

_MATMUL_DEF = OpDef("matmul", None, amp=AMP_WHITE)


def inner(x, y, name=None):
    return apply_fn("inner", jnp.inner, x, y, _opdef=_MATMUL_DEF)


def outer(x, y, name=None):
    return apply_fn("outer", lambda a, b: jnp.outer(a.reshape(-1), b.reshape(-1)), x, y, _opdef=_MATMUL_DEF)


def kron(x, y, name=None):
    return apply_fn("kron", jnp.kron, x, y)


# ---------- reductions ----------

def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    dt = dtype_mod.convert_dtype(dtype)
    return apply_fn("sum", lambda a: jnp.sum(a, axis=_axis(axis), dtype=dt, keepdims=keepdim), x)


def mean(x, axis=None, keepdim=False, name=None):
    return apply_fn("mean", lambda a: jnp.mean(a, axis=_axis(axis), keepdims=keepdim), x)


def max(x, axis=None, keepdim=False, name=None):
    return apply_fn("max", lambda a: jnp.max(a, axis=_axis(axis), keepdims=keepdim), x)


def min(x, axis=None, keepdim=False, name=None):
    return apply_fn("min", lambda a: jnp.min(a, axis=_axis(axis), keepdims=keepdim), x)


def amax(x, axis=None, keepdim=False, name=None):
    return max(x, axis, keepdim)


def amin(x, axis=None, keepdim=False, name=None):
    return min(x, axis, keepdim)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    dt = dtype_mod.convert_dtype(dtype)
    return apply_fn("prod", lambda a: jnp.prod(a, axis=_axis(axis), dtype=dt, keepdims=keepdim), x)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply_fn(
        "logsumexp", lambda a: jax.scipy.special.logsumexp(a, axis=_axis(axis), keepdims=keepdim), x,
        _opdef=_LSE_DEF,
    )


_LSE_DEF = OpDef("logsumexp", None, amp=AMP_BLACK)


def all(x, axis=None, keepdim=False, name=None):
    return apply_fn("all", lambda a: jnp.all(a, axis=_axis(axis), keepdims=keepdim), x)


def any(x, axis=None, keepdim=False, name=None):
    return apply_fn("any", lambda a: jnp.any(a, axis=_axis(axis), keepdims=keepdim), x)


def cumsum(x, axis=None, dtype=None, name=None):
    dt = dtype_mod.convert_dtype(dtype)

    def fn(a):
        if axis is None:
            return jnp.cumsum(a.reshape(-1), dtype=dt)
        return jnp.cumsum(a, axis=_axis(axis), dtype=dt)

    return apply_fn("cumsum", fn, x)


def cumprod(x, dim=None, dtype=None, name=None):
    dt = dtype_mod.convert_dtype(dtype)
    return apply_fn("cumprod", lambda a: jnp.cumprod(a, axis=_axis(dim), dtype=dt), x)


def _cum_extreme(x, axis, dtype, cmp):
    """Shared cummax/cummin: values + first-occurrence indices via associative scan."""
    dt = dtype_mod.convert_dtype(dtype)

    def fn(a):
        flat = axis is None
        arr = a.reshape(-1) if flat else a
        ax = 0 if flat else _axis(axis)
        idx0 = jnp.broadcast_to(
            jnp.arange(arr.shape[ax], dtype=dt).reshape([-1 if i == ax else 1 for i in range(arr.ndim)]),
            arr.shape,
        )

        def combine(l, r):
            lv, li = l
            rv, ri = r
            take_r = cmp(rv, lv)
            return jnp.where(take_r, rv, lv), jnp.where(take_r, ri, li)

        vals, idx = jax.lax.associative_scan(combine, (arr, idx0), axis=ax)
        return vals, idx

    return apply_fn("cum_extreme", fn, x)


def cummax(x, axis=None, dtype="int64", name=None):
    return _cum_extreme(x, axis, dtype, lambda r, l: r > l)


def cummin(x, axis=None, dtype="int64", name=None):
    return _cum_extreme(x, axis, dtype, lambda r, l: r < l)


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    dt = dtype_mod.convert_dtype(dtype)
    return apply_fn("nansum", lambda a: jnp.nansum(a, axis=_axis(axis), dtype=dt, keepdims=keepdim), x)


def nanmean(x, axis=None, keepdim=False, name=None):
    return apply_fn("nanmean", lambda a: jnp.nanmean(a, axis=_axis(axis), keepdims=keepdim), x)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply_fn("count_nonzero", lambda a: jnp.count_nonzero(a, axis=_axis(axis), keepdims=keepdim), x)


def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        return inputs

    def fn(*xs):
        out = xs[0]
        for a in xs[1:]:
            out = out + a
        return out

    return apply_fn("add_n", fn, *inputs)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_fn("trace", lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2), x)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_fn("diagonal", lambda a: jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2), x)


# ---------- logic / comparison ----------

def equal(x, y, name=None):
    return apply_fn("equal", jnp.equal, x, y)


def not_equal(x, y, name=None):
    return apply_fn("not_equal", jnp.not_equal, x, y)


def greater_than(x, y, name=None):
    return apply_fn("greater_than", jnp.greater, x, y)


def greater_equal(x, y, name=None):
    return apply_fn("greater_equal", jnp.greater_equal, x, y)


def less_than(x, y, name=None):
    return apply_fn("less_than", jnp.less, x, y)


def less_equal(x, y, name=None):
    return apply_fn("less_equal", jnp.less_equal, x, y)


def equal_all(x, y, name=None):
    return Tensor(jnp.array_equal(_u(x), _u(y)))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor(jnp.allclose(_u(x), _u(y), rtol=_u(rtol), atol=_u(atol), equal_nan=equal_nan))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_fn("isclose", lambda a, b: jnp.isclose(a, b, rtol=_u(rtol), atol=_u(atol), equal_nan=equal_nan), x, y)


def logical_and(x, y, out=None, name=None):
    return apply_fn("logical_and", jnp.logical_and, x, y)


def logical_or(x, y, out=None, name=None):
    return apply_fn("logical_or", jnp.logical_or, x, y)


def logical_xor(x, y, out=None, name=None):
    return apply_fn("logical_xor", jnp.logical_xor, x, y)


def logical_not(x, out=None, name=None):
    return apply_fn("logical_not", jnp.logical_not, x)


def bitwise_and(x, y, out=None, name=None):
    return apply_fn("bitwise_and", jnp.bitwise_and, x, y)


def bitwise_or(x, y, out=None, name=None):
    return apply_fn("bitwise_or", jnp.bitwise_or, x, y)


def bitwise_xor(x, y, out=None, name=None):
    return apply_fn("bitwise_xor", jnp.bitwise_xor, x, y)


def bitwise_not(x, out=None, name=None):
    return apply_fn("bitwise_not", jnp.bitwise_not, x)


def bitwise_left_shift(x, y, name=None):
    return apply_fn("bitwise_left_shift", jnp.left_shift, x, y)


def bitwise_right_shift(x, y, name=None):
    return apply_fn("bitwise_right_shift", jnp.right_shift, x, y)


# ---------- stat ----------

def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_fn(
        "std", lambda a: jnp.std(a, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim), x
    )


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_fn(
        "var", lambda a: jnp.var(a, axis=_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim), x
    )


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    def fn(a):
        if mode == "min" or jnp.issubdtype(a.dtype, jnp.integer):
            return jnp.quantile(a.astype(jnp.float32), 0.5, axis=_axis(axis), keepdims=keepdim, method="lower")
        return jnp.median(a, axis=_axis(axis), keepdims=keepdim)

    return apply_fn("median", fn, x)


def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply_fn("nanmedian", lambda a: jnp.nanmedian(a, axis=_axis(axis), keepdims=keepdim), x)


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    return apply_fn(
        "quantile",
        lambda a: jnp.quantile(a, jnp.asarray(_u(q)), axis=_axis(axis), keepdims=keepdim, method=interpolation),
        x,
    )


def histogram(x, bins=100, min=0, max=0, name=None):
    a = _u(x)
    lo, hi = (_u(min), _u(max)) if (_u(min) != 0 or _u(max) != 0) else (a.min(), a.max())
    h, _ = jnp.histogram(a, bins=bins, range=(lo, hi))
    return Tensor(h)


def bincount(x, weights=None, minlength=0, name=None):
    return Tensor(jnp.bincount(_u(x), weights=_u(weights) if weights is not None else None, minlength=minlength))
