"""Search / sort ops (reference: python/paddle/tensor/search.py)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtype_mod
from ..core.op_registry import apply_fn
from ..core.tensor import Tensor, unwrap


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    dt = dtype_mod.convert_dtype(dtype)

    def fn(a):
        if axis is None:
            out = jnp.argmax(a.reshape(-1))
            return out.reshape((1,) * a.ndim).astype(dt) if keepdim else out.astype(dt)
        out = jnp.argmax(a, axis=int(unwrap(axis)), keepdims=keepdim)
        return out.astype(dt)

    return apply_fn("argmax", fn, x)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    dt = dtype_mod.convert_dtype(dtype)

    def fn(a):
        if axis is None:
            out = jnp.argmin(a.reshape(-1))
            return out.reshape((1,) * a.ndim).astype(dt) if keepdim else out.astype(dt)
        return jnp.argmin(a, axis=int(unwrap(axis)), keepdims=keepdim).astype(dt)

    return apply_fn("argmin", fn, x)


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def fn(a):
        idx = jnp.argsort(a, axis=axis, stable=stable, descending=descending)
        return idx.astype(jnp.int64)

    return apply_fn("argsort", fn, x)


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def fn(a):
        out = jnp.sort(a, axis=axis, stable=stable, descending=descending)
        return out

    return apply_fn("sort", fn, x)


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    k = int(unwrap(k))

    def fn(a):
        ax = axis if axis is not None else -1
        ax = ax % a.ndim
        src = a if largest else -a
        src_last = jnp.moveaxis(src, ax, -1)
        import jax

        vals, idx = jax.lax.top_k(src_last, k)
        if not largest:
            vals = -vals
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx.astype(jnp.int64), -1, ax)

    return apply_fn("topk", fn, x)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def fn(a):
        ax = axis % a.ndim
        srt = jnp.sort(a, axis=ax)
        idx = jnp.argsort(a, axis=ax)
        v = jnp.take(srt, k - 1, axis=ax)
        i = jnp.take(idx, k - 1, axis=ax).astype(jnp.int64)
        if keepdim:
            v, i = jnp.expand_dims(v, ax), jnp.expand_dims(i, ax)
        return v, i

    return apply_fn("kthvalue", fn, x)


def mode(x, axis=-1, keepdim=False, name=None):
    a = np.asarray(unwrap(x))
    ax = axis % a.ndim
    srt = np.sort(a, axis=ax)
    # most frequent value per slice
    from scipy import stats  # available via numpy ecosystem; fallback below if missing

    # compute with keepdims=True unconditionally: mixing scipy's squeezed
    # output with a second squeeze raised AxisError for keepdim=False on
    # 2-D inputs (caught by the round-5 numeric op sweep)
    try:
        vals_k = np.asarray(stats.mode(a, axis=ax, keepdims=True).mode)
    except Exception:
        vals_k = np.expand_dims(np.apply_along_axis(
            lambda v: np.bincount(v.astype(np.int64)).argmax(), ax, a), ax)
    idx = np.argmax(a == vals_k, axis=ax)
    vals = vals_k if keepdim else np.squeeze(vals_k, ax)
    if keepdim:
        idx = np.expand_dims(idx, ax)
    return Tensor(jnp.asarray(vals.astype(a.dtype))), Tensor(jnp.asarray(idx.astype(np.int64)))


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    def fn(s, v):
        out = jnp.searchsorted(s, v, side="right" if right else "left")
        return out.astype(jnp.int32 if out_int32 else jnp.int64)

    return apply_fn("searchsorted", fn, sorted_sequence, values)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)
