"""Top-level namespace completion (reference: python/paddle/__init__.py
__all__): the in-place op family (``x.add_(y)`` semantics via payload
rebinding) plus the remaining standalone functions."""

from __future__ import annotations

import itertools as _it

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtype_mod
from ..core.op_registry import apply_fn
from ..core.tensor import Tensor, unwrap
from ..framework.random import next_key

__all__ = [
    "iinfo", "finfo", "dtype", "float8_e4m3fn", "float8_e5m2",
    "mm", "pdist", "hstack", "vstack", "dstack", "column_stack", "row_stack",
    "cartesian_prod", "combinations", "log_normal", "standard_gamma",
    "shape", "tolist", "is_grad_enabled", "rank", "LazyGuard", "check_shape",
    "disable_signal_handler", "get_cuda_rng_state", "set_cuda_rng_state",
    "CUDAPinnedPlace", "batch",
]

# dtype objects (reference: paddle.dtype + float8 members)
dtype = jnp.dtype
float8_e4m3fn = jnp.float8_e4m3fn
float8_e5m2 = jnp.float8_e5m2


def iinfo(dt):
    return jnp.iinfo(dtype_mod.convert_dtype(dt))


def finfo(dt):
    return jnp.finfo(dtype_mod.convert_dtype(dt))


def mm(input, mat2, name=None):
    from .math import matmul

    return matmul(input, mat2)


def pdist(x, p=2.0, name=None):
    """Pairwise distances of rows, condensed upper-triangle (reference: pdist)."""

    def fn(a):
        n = a.shape[0]
        d = a[:, None] - a[None]
        if p == 2.0:
            full = jnp.sqrt(jnp.maximum(jnp.sum(d * d, -1), 0.0))
        elif p == float("inf"):
            full = jnp.max(jnp.abs(d), -1)
        else:
            full = jnp.sum(jnp.abs(d) ** p, -1) ** (1.0 / p)
        iu = jnp.triu_indices(n, k=1)
        return full[iu]

    return apply_fn("pdist", fn, x)


def _stack_family(op_name, fn):
    def f(x, name=None):
        args = [t if isinstance(t, Tensor) else Tensor(np.asarray(t))
                for t in x]
        return apply_fn(op_name, lambda *a: fn(a), *args)

    f.__name__ = op_name
    return f


hstack = _stack_family("hstack", jnp.hstack)
vstack = _stack_family("vstack", jnp.vstack)
dstack = _stack_family("dstack", jnp.dstack)
column_stack = _stack_family("column_stack", jnp.column_stack)
row_stack = vstack  # reference alias


def cartesian_prod(x, name=None):
    """Cartesian product of 1-D tensors (reference: cartesian_prod)."""
    args = [t if isinstance(t, Tensor) else Tensor(np.asarray(t)) for t in x]

    def fn(*arrs):
        grids = jnp.meshgrid(*arrs, indexing="ij")
        return jnp.stack([g.ravel() for g in grids], axis=-1)

    return apply_fn("cartesian_prod", fn, *args)


def combinations(x, r=2, with_replacement=False, name=None):
    """r-combinations of a 1-D tensor's elements (reference: combinations)."""

    def fn(a):
        n = a.shape[0]
        idx_iter = (_it.combinations_with_replacement(range(n), r)
                    if with_replacement else _it.combinations(range(n), r))
        idx = np.array(list(idx_iter), np.int32).reshape(-1, r)
        return a[jnp.asarray(idx)]

    return apply_fn("combinations", fn, x)


def log_normal(mean=1.0, std=2.0, shape=None, dtype=None, name=None):
    """Log-normal samples: exp(N(mean, std)) (reference: log_normal)."""
    dt = dtype_mod.convert_dtype(dtype) or dtype_mod.get_default_dtype()
    shp = tuple(int(unwrap(s)) for s in (shape or [1]))
    return Tensor(jnp.exp(jax.random.normal(next_key(), shp) * std + mean).astype(dt))


def standard_gamma(alpha, name=None):
    def fn(a):
        return jax.random.gamma(next_key(), a)

    return apply_fn("standard_gamma", fn,
                    alpha if isinstance(alpha, Tensor) else Tensor(np.asarray(alpha, np.float32)))


def shape(input):
    """Runtime shape as an int tensor (reference: paddle.shape)."""
    return Tensor(np.asarray(unwrap(input).shape, np.int32))


def tolist(x):
    return x.tolist() if isinstance(x, Tensor) else np.asarray(x).tolist()


def is_grad_enabled():
    from ..core import autograd_engine

    return autograd_engine.grad_enabled()


def rank(input):
    """Tensor rank (ndim) as a 0-D tensor (reference: paddle.rank)."""
    return Tensor(np.asarray(unwrap(input).ndim, np.int32))


class LazyGuard:
    """Deferred-init guard (reference: LazyGuard). Initialization is eager in
    this framework; the guard is a no-op context for porting compatibility."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def check_shape(x, expected):
    got = list(unwrap(x).shape)
    exp = [int(s) if s is not None else None for s in expected]
    if len(got) != len(exp):
        raise ValueError(f"rank mismatch: got {got}, expected {exp}")
    for g, e in zip(got, exp):
        if e is not None and e != -1 and g != e:
            raise ValueError(f"shape mismatch: got {got}, expected {exp}")
    return True


def disable_signal_handler():
    pass  # no native signal handlers installed


def get_cuda_rng_state():
    from ..framework.random import get_rng_state

    return [get_rng_state()]


def set_cuda_rng_state(state):
    from ..framework.random import set_rng_state

    if isinstance(state, (list, tuple)) and state:
        set_rng_state(state[0])


class CUDAPinnedPlace:
    """Place stub (host staging is XLA's concern on TPU)."""


def batch(reader, batch_size, drop_last=False):
    """Deprecated reader decorator (reference: paddle.batch)."""

    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched


# ---------------------------------------------------------------------------
# in-place variants: x.op_(...) rebinds the payload (the tape keeps the
# functional result, matching the reference's view-free inplace semantics)
# ---------------------------------------------------------------------------

# base names whose name_ form the reference exports at top level
# (where_ is special-cased below: its in-place target is x, not the condition)
INPLACE_BASES = [
    "addmm", "t", "cumsum", "cumprod", "logit", "equal", "cos",
    "tan", "unsqueeze", "logical_and", "less_than", "squeeze", "floor_divide",
    "remainder", "logical_or", "bitwise_and", "bitwise_or", "bitwise_xor",
    "bitwise_not", "less_equal", "triu", "sin", "mod", "abs", "tril", "pow",
    "acos", "expm1", "sinh", "sinc", "neg", "lgamma", "gammaincc", "gammainc",
    "square", "divide", "gammaln", "atan", "gcd", "lcm", "cast",
    "greater_equal", "erf", "greater_than", "tanh", "transpose", "flatten",
    "multiply", "logical_not", "log", "log2", "log10", "trunc", "frac",
    "digamma", "renorm", "multigammaln", "nan_to_num", "ldexp", "i0",
    "polygamma", "copysign", "bitwise_left_shift", "bitwise_right_shift",
    "masked_fill", "masked_scatter", "hypot", "floor_mod",
]


def _make_inplace(base_fn, name):
    def f(x, *args, **kwargs):
        out = base_fn(x, *args, **kwargs)
        return x._replace_(out._data, out._node, out._out_idx)

    f.__name__ = name
    return f


def _random_fill(name, sampler):
    """In-place random fill: x is overwritten with samples of its shape.
    Goes through _replace_ so the stale autograd node is dropped — the new
    value no longer depends on x's producers."""

    def f(x, *args, **kwargs):
        kwargs.pop("name", None)
        new = sampler(tuple(x.shape), *args, **kwargs).astype(x.dtype)
        return x._replace_(new)

    f.__name__ = name
    return f


log_normal_ = _random_fill(
    "log_normal_",
    lambda shp, mean=1.0, std=2.0: jnp.exp(
        jax.random.normal(next_key(), shp) * std + mean))

cauchy_ = _random_fill(
    "cauchy_",
    lambda shp, loc=0.0, scale=1.0: loc + scale * jax.random.cauchy(
        next_key(), shp))


def _geometric_sample(shp, probs):
    # continuous log(u)/log1p(-p), matching the reference's
    # x.uniform_().log_().divide_(log1p(-p)) (creation.py geometric_ — no floor)
    p = unwrap(probs) if isinstance(probs, Tensor) else jnp.asarray(float(probs))
    u = jax.random.uniform(next_key(), shp, minval=1e-7)
    return jnp.log(u) / jnp.log1p(-p)


geometric_ = _random_fill("geometric_", _geometric_sample)


def where_(condition, x, y, name=None):
    """In-place where: x receives where(condition, x, y) (reference: the
    in-place target is x, NOT the first positional arg — excluded from the
    generic _make_inplace family for exactly that reason)."""
    from .manipulation import where as _where

    out = _where(condition, x, y)
    return x._replace_(out._data, out._node, out._out_idx)


def install_inplace_variants(namespace):
    """Create the ``<op>_`` family from existing ops in ``namespace`` and
    install them both as module attributes and Tensor methods."""
    created = {}
    for base in INPLACE_BASES:
        fn = namespace.get(base)
        if fn is None:
            continue
        name = base + "_"
        wrapper = _make_inplace(fn, name)
        created[name] = wrapper
        if not hasattr(Tensor, name):
            setattr(Tensor, name, wrapper)
    for name, fn in (("log_normal_", log_normal_), ("cauchy_", cauchy_),
                     ("geometric_", geometric_), ("where_", where_)):
        created[name] = fn
        if name != "where_" and not hasattr(Tensor, name):
            setattr(Tensor, name, fn)
    return created
