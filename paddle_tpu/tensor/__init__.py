"""Tensor op namespaces + method installation onto the Tensor class.

Mirrors how the reference attaches ~320 methods to its eager Tensor
(python/paddle/tensor/__init__.py ``tensor_method_func`` list + monkey-patch in
base/dygraph/math_op_patch.py).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.op_registry import apply_fn
from ..core.tensor import Tensor, unwrap
from . import creation, extras, linalg, manipulation, math, random, search
from . import toplevel_extras
from .creation import *  # noqa: F401,F403
from .extras import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .toplevel_extras import *  # noqa: F401,F403


def einsum(equation, *operands):
    """paddle.einsum (reference: python/paddle/tensor/einsum.py) — MXU-friendly via XLA dot_general."""
    return apply_fn("einsum", lambda *ops: jnp.einsum(equation, *ops), *operands)


def _index_prepare(item):
    if isinstance(item, tuple):
        return tuple(unwrap(i) for i in item)
    return unwrap(item)


def _getitem(self, item):
    idx = _index_prepare(item)
    return apply_fn("getitem", lambda a: a[idx], self)


def _setitem(self, item, value):
    idx = _index_prepare(item)
    if isinstance(value, Tensor):
        out = apply_fn("setitem", lambda a, v: a.at[idx].set(v), self, value)
    else:
        out = apply_fn("setitem", lambda a: a.at[idx].set(value), self)
    self._replace_(out._data, out._node, out._out_idx)


def _iter(self):
    for i in range(self.shape[0]):
        yield self[i]


def _install():
    T = Tensor
    # arithmetic operators
    T.__add__ = lambda s, o: math.add(s, o)
    T.__radd__ = lambda s, o: math.add(s, o)
    T.__sub__ = lambda s, o: math.subtract(s, o)
    T.__rsub__ = lambda s, o: math.subtract(Tensor(o) if not isinstance(o, Tensor) else o, s)
    T.__mul__ = lambda s, o: math.multiply(s, o)
    T.__rmul__ = lambda s, o: math.multiply(s, o)
    T.__truediv__ = lambda s, o: math.divide(s, o)
    T.__rtruediv__ = lambda s, o: math.divide(Tensor(o) if not isinstance(o, Tensor) else o, s)
    T.__floordiv__ = lambda s, o: math.floor_divide(s, o)
    T.__mod__ = lambda s, o: math.mod(s, o)
    T.__pow__ = lambda s, o: math.pow(s, o)
    T.__rpow__ = lambda s, o: math.pow(Tensor(o) if not isinstance(o, Tensor) else o, s)
    T.__neg__ = lambda s: math.neg(s)
    T.__abs__ = lambda s: math.abs(s)
    T.__matmul__ = lambda s, o: math.matmul(s, o)
    T.__rmatmul__ = lambda s, o: math.matmul(Tensor(o) if not isinstance(o, Tensor) else o, s)
    # comparison
    T.__eq__ = lambda s, o: math.equal(s, o)
    T.__ne__ = lambda s, o: math.not_equal(s, o)
    T.__lt__ = lambda s, o: math.less_than(s, o)
    T.__le__ = lambda s, o: math.less_equal(s, o)
    T.__gt__ = lambda s, o: math.greater_than(s, o)
    T.__ge__ = lambda s, o: math.greater_equal(s, o)
    T.__invert__ = lambda s: math.logical_not(s) if s.dtype == jnp.bool_ else math.bitwise_not(s)
    T.__and__ = lambda s, o: math.logical_and(s, o) if s.dtype == jnp.bool_ else math.bitwise_and(s, o)
    T.__or__ = lambda s, o: math.logical_or(s, o) if s.dtype == jnp.bool_ else math.bitwise_or(s, o)
    T.__xor__ = lambda s, o: math.logical_xor(s, o) if s.dtype == jnp.bool_ else math.bitwise_xor(s, o)
    # indexing / iteration
    T.__getitem__ = _getitem
    T.__setitem__ = _setitem
    T.__iter__ = _iter

    # signal methods attach too (reference tensor_method_func includes them)
    def _stft(self, *a, **k):
        from .. import signal

        return signal.stft(self, *a, **k)

    def _istft(self, *a, **k):
        from .. import signal

        return signal.istft(self, *a, **k)

    T.stft = _stft
    T.istft = _istft

    methods = {}
    for mod in (math, manipulation, linalg, creation, search, extras):
        for name in dir(mod):
            fn = getattr(mod, name)
            if callable(fn) and not name.startswith("_") and name not in ("Tensor",):
                methods.setdefault(name, fn)
    # creation fns that take x first can't all be methods; install the standard set
    method_names = [
        # math
        "add", "subtract", "multiply", "divide", "floor_divide", "mod", "remainder", "pow",
        "maximum", "minimum", "fmax", "fmin", "exp", "expm1", "log", "log2", "log10", "log1p",
        "sqrt", "rsqrt", "abs", "sign", "neg", "sin", "cos", "tan", "asin", "acos", "atan",
        "sinh", "cosh", "tanh", "asinh", "acosh", "atanh", "ceil", "floor", "round", "trunc",
        "frac", "reciprocal", "square", "sigmoid", "erf", "erfinv", "lgamma", "digamma",
        "isnan", "isinf", "isfinite", "nan_to_num", "clip", "scale", "lerp", "matmul",
        "inner", "outer", "kron", "sum", "mean", "max", "min", "amax", "amin", "prod",
        "logsumexp", "all", "any", "cumsum", "cumprod", "cummax", "cummin", "nansum",
        "nanmean", "count_nonzero", "trace", "diagonal", "equal", "not_equal",
        "greater_than", "greater_equal", "less_than", "less_equal", "equal_all", "allclose",
        "isclose", "logical_and", "logical_or", "logical_xor", "logical_not", "bitwise_and",
        "bitwise_or", "bitwise_xor", "bitwise_not", "std", "var", "median", "nanmedian",
        "quantile", "histogram", "bincount", "atan2", "heaviside", "deg2rad", "rad2deg",
        "angle", "conj", "real", "imag", "logaddexp",
        # manipulation
        "reshape", "reshape_", "flatten", "squeeze", "unsqueeze", "transpose", "t",
        "moveaxis", "swapaxes", "split", "chunk", "unbind", "tile", "expand", "expand_as",
        "broadcast_to", "flip", "rot90", "roll", "cast", "gather", "gather_nd", "scatter",
        "scatter_", "scatter_nd_add", "index_select", "index_sample", "index_add",
        "masked_select", "masked_fill", "take_along_axis", "put_along_axis", "take",
        "repeat_interleave", "unique", "unique_consecutive", "where", "nonzero",
        "as_real", "as_complex", "tensordot", "view", "view_as", "pad",
        # linalg
        "dot", "bmm", "mv", "norm", "cholesky", "qr", "svd", "inv", "pinv", "det",
        "slogdet", "solve", "triangular_solve", "matrix_power", "cross", "multiply",
        # search
        "argmax", "argmin", "argsort", "sort", "topk", "kthvalue", "mode",
        "searchsorted", "bucketize",
        # creation-ish
        "tril", "triu", "diag",
    ]
    # extras ops all take x first: install every public one as a method
    # (reference: tensor_method_func includes the full long tail)
    method_names += [n for n in extras.__all__
                     if n not in ("is_tensor", "block_diag")]
    for name in method_names:
        if name in methods and not hasattr(T, name):
            setattr(T, name, methods[name])
        elif name in methods:
            # overwrite slot-placeholder methods like astype-based ones only if absent
            if name not in ("astype",):
                setattr(T, name, methods[name])

    # in-place variants: rebind payload, preserve graph semantics
    # ONE in-place wrapper implementation (toplevel_extras._make_inplace)
    for name in ["add", "subtract", "multiply", "divide", "clip", "scale", "exp", "sqrt",
                 "rsqrt", "floor", "ceil", "round", "reciprocal", "tanh", "sigmoid",
                 "cast", "flatten", "squeeze", "unsqueeze", "transpose"]:
        if name in methods:
            setattr(T, name + "_",
                    toplevel_extras._make_inplace(methods[name], name + "_"))

    def astype(self, dtype):
        return manipulation.cast(self, dtype)

    T.astype = astype
    T.mm = methods["matmul"]
    T.abs_ = toplevel_extras._make_inplace(methods["abs"], "abs_")
    T.zero_ = lambda s: s.set_value(jnp.zeros_like(s._data))
    T.fill_ = lambda s, v: s.set_value(jnp.full_like(s._data, v))
    T.numel = lambda s: creation.numel(s)
    T.element_size = lambda s: s._data.dtype.itemsize
    T.dim = lambda s: s._data.ndim
    T.rank = lambda s: s._data.ndim
    T.nelement = lambda s: creation.numel(s)


_install()

# generate the <op>_ in-place family from the installed functional ops and
# re-export them at module level (reference: paddle top-level *_ exports)
_inplace_fns = toplevel_extras.install_inplace_variants(globals())
globals().update(_inplace_fns)
