"""Tensor creation ops (reference: python/paddle/tensor/creation.py)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtype_mod
from ..core.op_registry import apply_fn
from ..core.tensor import Tensor, unwrap


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    if isinstance(data, Tensor):
        t = Tensor(data._data, dtype=dtype, stop_gradient=stop_gradient)
        t._node, t._out_idx = data._node, data._out_idx
        return t
    return Tensor(data, dtype=dtype, stop_gradient=stop_gradient)


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._data))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(unwrap(s)) for s in shape)


def zeros(shape, dtype=None, name=None):
    dt = dtype_mod.convert_dtype(dtype) or dtype_mod.get_default_dtype()
    shape = _shape(shape)
    return apply_fn("zeros", lambda: jnp.zeros(shape, dt))


def ones(shape, dtype=None, name=None):
    dt = dtype_mod.convert_dtype(dtype) or dtype_mod.get_default_dtype()
    shape = _shape(shape)
    return apply_fn("ones", lambda: jnp.ones(shape, dt))


def full(shape, fill_value, dtype=None, name=None):
    fill_value = unwrap(fill_value)
    dt = dtype_mod.convert_dtype(dtype)
    shape = _shape(shape)
    return apply_fn("full", lambda: jnp.full(shape, fill_value, dt))


def zeros_like(x, dtype=None, name=None):
    return apply_fn("zeros_like", lambda a: jnp.zeros_like(a, dtype=dtype_mod.convert_dtype(dtype)), x)


def ones_like(x, dtype=None, name=None):
    return apply_fn("ones_like", lambda a: jnp.ones_like(a, dtype=dtype_mod.convert_dtype(dtype)), x)


def full_like(x, fill_value, dtype=None, name=None):
    return apply_fn(
        "full_like", lambda a: jnp.full_like(a, unwrap(fill_value), dtype=dtype_mod.convert_dtype(dtype)), x
    )


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    start, end, step = unwrap(start), unwrap(end), unwrap(step)
    if end is None:
        start, end = 0, start
    dt = dtype_mod.convert_dtype(dtype)
    return Tensor(jnp.arange(start, end, step, dtype=dt))


def linspace(start, stop, num, dtype=None, name=None):
    return Tensor(jnp.linspace(unwrap(start), unwrap(stop), int(unwrap(num)), dtype=dtype_mod.convert_dtype(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(
        jnp.logspace(unwrap(start), unwrap(stop), int(unwrap(num)), base=unwrap(base), dtype=dtype_mod.convert_dtype(dtype))
    )


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(int(num_rows), int(num_columns) if num_columns is not None else None,
                          dtype=dtype_mod.convert_dtype(dtype) or dtype_mod.get_default_dtype()))


def diag(x, offset=0, padding_value=0, name=None):
    def fn(a):
        if a.ndim == 1 and padding_value != 0:
            n = a.shape[0] + abs(offset)
            base = jnp.full((n, n), padding_value, a.dtype)
            idx = jnp.arange(a.shape[0])
            r, c = (idx, idx + offset) if offset >= 0 else (idx - offset, idx)
            return base.at[r, c].set(a)
        return jnp.diag(a, k=offset)

    return apply_fn("diag", fn, x)


def diagflat(x, offset=0, name=None):
    return apply_fn("diagflat", lambda a: jnp.diagflat(a, k=offset), x)


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    def fn(a):
        n = a.shape[-1]
        idx = jnp.arange(n)
        r, c = (idx, idx + offset) if offset >= 0 else (idx - offset, idx)
        full = jnp.zeros(a.shape[:-1] + (n + abs(offset), n + abs(offset)), a.dtype)
        full = full.at[..., r, c].set(a)
        return jnp.moveaxis(full, (-2, -1), (dim1, dim2))

    return apply_fn("diag_embed", fn, x)


def tril(x, diagonal=0, name=None):
    return apply_fn("tril", lambda a: jnp.tril(a, k=diagonal), x)


def triu(x, diagonal=0, name=None):
    return apply_fn("triu", lambda a: jnp.triu(a, k=diagonal), x)


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    outs = jnp.meshgrid(*[unwrap(a) for a in args], indexing="ij")
    return [Tensor(o) for o in outs]


def assign(x, output=None):
    data = unwrap(x)
    if not isinstance(data, jnp.ndarray):
        data = jnp.asarray(data)
    if output is not None:
        output.set_value(data)
        return output
    return Tensor(data)


def clone(x, name=None):
    return apply_fn("clone", lambda a: a + 0, x)


def numel(x, name=None):
    return Tensor(jnp.asarray(int(np.prod(x.shape)) if x.shape else 1))


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=dtype_mod.convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = col if col is not None else row
    r, c = np.triu_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=dtype_mod.convert_dtype(dtype)))


def complex(real, imag, name=None):
    return apply_fn("complex", lambda r, i: jnp.asarray(r) + 1j * jnp.asarray(i), real, imag)


def polar(abs_t, angle, name=None):
    return apply_fn("polar", lambda r, a: r * jnp.exp(1j * a.astype(jnp.complex64)), abs_t, angle)
