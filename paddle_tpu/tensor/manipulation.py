"""Shape / layout / indexing ops (reference: python/paddle/tensor/manipulation.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtype_mod
from ..core.op_registry import apply_fn
from ..core.tensor import Tensor, unwrap


def _u(x):
    return unwrap(x)


def _resolve_shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in np.asarray(shape._data))
    return tuple(int(_u(s)) for s in shape) if isinstance(shape, (list, tuple)) else (int(shape),)


def reshape(x, shape, name=None):
    shp = _resolve_shape(shape)
    return apply_fn("reshape", lambda a: jnp.reshape(a, shp), x)


def reshape_(x, shape, name=None):
    out = reshape(x, shape)
    return x._replace_(out._data, out._node, out._out_idx)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def fn(a):
        nd = a.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = a.shape[:s] + (-1,) + a.shape[e + 1:]
        return jnp.reshape(a, new_shape)

    return apply_fn("flatten", fn, x)


def squeeze(x, axis=None, name=None):
    def fn(a):
        if axis is None:
            return jnp.squeeze(a)
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        axes = tuple(int(_u(ax)) % a.ndim for ax in axes)
        axes = tuple(ax for ax in axes if a.shape[ax] == 1)
        return jnp.squeeze(a, axis=axes) if axes else a

    return apply_fn("squeeze", fn, x)


def unsqueeze(x, axis, name=None):
    def fn(a):
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        out = a
        for ax in sorted(int(_u(v)) if not isinstance(v, int) else v for v in axes):
            out = jnp.expand_dims(out, ax)
        return out

    return apply_fn("unsqueeze", fn, x)


def transpose(x, perm, name=None):
    perm = [int(_u(p)) for p in perm]
    return apply_fn("transpose", lambda a: jnp.transpose(a, perm), x)


def t(x, name=None):
    return apply_fn("t", lambda a: a.T if a.ndim >= 2 else a, x)


def moveaxis(x, source, destination, name=None):
    return apply_fn("moveaxis", lambda a: jnp.moveaxis(a, source, destination), x)


def swapaxes(x, axis0, axis1, name=None):
    return apply_fn("swapaxes", lambda a: jnp.swapaxes(a, axis0, axis1), x)


swapdims = swapaxes


def concat(x, axis=0, name=None):
    axis = int(_u(axis))
    tensors = list(x)
    return apply_fn("concat", lambda *xs: jnp.concatenate(xs, axis=axis), *tensors)


def stack(x, axis=0, name=None):
    tensors = list(x)
    return apply_fn("stack", lambda *xs: jnp.stack(xs, axis=axis), *tensors)


def split(x, num_or_sections, axis=0, name=None):
    axis = int(_u(axis))

    def fn(a):
        if isinstance(num_or_sections, int):
            return tuple(jnp.split(a, num_or_sections, axis=axis))
        secs = [int(_u(s)) for s in num_or_sections]
        known = 0
        for s in secs:
            if s >= 0:
                known += s
        secs = [s if s >= 0 else a.shape[axis] - known for s in secs]
        points = np.cumsum(secs)[:-1].tolist()
        return tuple(jnp.split(a, points, axis=axis))

    return list(apply_fn("split", fn, x))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    n = x.shape[int(axis)]

    def fn(a):
        return tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(a, n, axis=axis))

    return list(apply_fn("unbind", fn, x))


def unstack(x, axis=0, num=None, name=None):
    return unbind(x, axis)


def tile(x, repeat_times, name=None):
    reps = tuple(int(_u(r)) for r in repeat_times) if isinstance(repeat_times, (list, tuple)) else (int(_u(repeat_times)),)
    return apply_fn("tile", lambda a: jnp.tile(a, reps), x)


def expand(x, shape, name=None):
    shp = _resolve_shape(shape)

    def fn(a):
        tgt = list(shp)
        # -1 means keep original dim
        off = len(tgt) - a.ndim
        for i in range(len(tgt)):
            if tgt[i] == -1:
                tgt[i] = a.shape[i - off]
        return jnp.broadcast_to(a, tuple(tgt))

    return apply_fn("expand", fn, x)


def expand_as(x, y, name=None):
    shp = tuple(y.shape)
    return apply_fn("expand_as", lambda a: jnp.broadcast_to(a, shp), x)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    arrs = jnp.broadcast_arrays(*[_u(i) for i in inputs])
    return [Tensor(a) for a in arrs]


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def flip(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return apply_fn("flip", lambda a: jnp.flip(a, axis=tuple(axes)), x)


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply_fn("rot90", lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), x)


def roll(x, shifts, axis=None, name=None):
    return apply_fn("roll", lambda a: jnp.roll(a, shifts, axis=axis), x)


def cast(x, dtype):
    dt = dtype_mod.convert_dtype(dtype)
    return apply_fn("cast", lambda a: a.astype(dt), x)


def gather(x, index, axis=0, name=None):
    axis = int(_u(axis))
    return apply_fn("gather", lambda a, i: jnp.take(a, i.reshape(-1), axis=axis), x, index)


def gather_nd(x, index, name=None):
    def fn(a, idx):
        return a[tuple(jnp.moveaxis(idx, -1, 0))]

    return apply_fn("gather_nd", fn, x, index)


def scatter(x, index, updates, overwrite=True, name=None):
    def fn(a, i, u):
        i = i.reshape(-1)
        if overwrite:
            return a.at[i].set(u)
        zeroed = a.at[i].set(jnp.zeros_like(u))
        return zeroed.at[i].add(u)

    return apply_fn("scatter", fn, x, index, updates)


def scatter_(x, index, updates, overwrite=True, name=None):
    out = scatter(x, index, updates, overwrite)
    return x._replace_(out._data, out._node, out._out_idx)


def scatter_nd_add(x, index, updates, name=None):
    def fn(a, idx, u):
        return a.at[tuple(jnp.moveaxis(idx, -1, 0))].add(u)

    return apply_fn("scatter_nd_add", fn, x, index, updates)


def scatter_nd(index, updates, shape, name=None):
    zero = Tensor(jnp.zeros(_resolve_shape(shape), dtype=_u(updates).dtype))
    return scatter_nd_add(zero, index, updates)


def index_select(x, index, axis=0, name=None):
    return apply_fn("index_select", lambda a, i: jnp.take(a, i.reshape(-1), axis=int(_u(axis))), x, index)


def index_sample(x, index, name=None):
    return apply_fn("index_sample", lambda a, i: jnp.take_along_axis(a, i, axis=1), x, index)


def index_add(x, index, axis, value, name=None):
    import builtins

    def fn(a, i, v):
        # builtins.slice: this module's own `slice` op shadows the builtin
        # (caught by the round-5 numeric op sweep — TypeError at call time)
        full = builtins.slice(None)
        return a.at[(full,) * (axis % a.ndim) + (i.reshape(-1),)].add(v)

    return apply_fn("index_add", fn, x, index, value)


def index_put(x, indices, value, accumulate=False, name=None):
    def fn(a, v, *idx):
        ii = tuple(idx)
        return a.at[ii].add(v) if accumulate else a.at[ii].set(v)

    return apply_fn("index_put", fn, x, value, *indices)


def masked_select(x, mask, name=None):
    a, m = _u(x), _u(mask)
    return Tensor(a[np.asarray(m)])


def masked_fill(x, mask, value, name=None):
    return apply_fn("masked_fill", lambda a, m: jnp.where(m, _u(value), a), x, mask)


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return apply_fn("take_along_axis", lambda a, i: jnp.take_along_axis(a, i, axis=axis), arr, indices)


def put_along_axis(arr, indices, values, axis, reduce="assign", include_self=True, broadcast=True, name=None):
    def fn(a, i, v):
        v = jnp.broadcast_to(v, i.shape) if hasattr(v, "shape") else v
        if reduce == "assign":
            return jnp.put_along_axis(a, i, v, axis=axis, inplace=False)
        # build explicit index grid for scatter
        idx = [jnp.arange(s).reshape([-1 if d == k else 1 for d in range(a.ndim)]) for k, s in enumerate(a.shape)]
        idx = [jnp.broadcast_to(g, i.shape) for g in idx]
        idx[axis % a.ndim] = i
        gather = tuple(idx)
        if reduce == "mean":
            # mean over scattered values (+ original when include_self)
            sums = a.at[gather].add(v) if include_self else jnp.zeros_like(a).at[gather].add(v)
            cnts = jnp.full(a.shape, 1 if include_self else 0, jnp.int32).at[gather].add(1)
            touched = jnp.zeros(a.shape, bool).at[gather].set(True)
            mean = sums / jnp.maximum(cnts, 1).astype(a.dtype)
            return jnp.where(touched, mean, a)
        at = a.at[gather]
        return {"add": at.add, "mul": at.multiply, "multiply": at.multiply,
                "amin": at.min, "amax": at.max}[reduce](v)

    return apply_fn("put_along_axis", fn, arr, indices, values)


def take(x, index, mode="raise", name=None):
    return apply_fn("take", lambda a, i: jnp.take(a.reshape(-1), i.reshape(-1), mode="clip" if mode != "raise" else None).reshape(_u(index).shape), x, index)


def slice(input, axes, starts, ends, name=None):
    import builtins

    def fn(a):
        sl = [builtins.slice(None)] * a.ndim
        for ax, s, e in zip(axes, starts, ends):
            sl[ax] = builtins.slice(int(_u(s)), int(_u(e)))
        return a[tuple(sl)]

    return apply_fn("slice", fn, input)


def strided_slice(x, axes, starts, ends, strides, name=None):
    import builtins

    def fn(a):
        sl = [builtins.slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            sl[ax] = builtins.slice(int(_u(s)), int(_u(e)), int(_u(st)))
        return a[tuple(sl)]

    return apply_fn("strided_slice", fn, x)


def crop(x, shape=None, offsets=None, name=None):
    import builtins

    shp = _resolve_shape(shape)
    offs = [int(_u(o)) for o in (offsets or [0] * len(shp))]

    def fn(a):
        sl = tuple(builtins.slice(o, o + s) for o, s in zip(offs, shp))
        return a[sl]

    return apply_fn("crop", fn, x)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    def fn(a):
        p = [int(_u(v)) for v in pad]
        nd = a.ndim
        if len(p) == 2 * nd:
            width = [(p[2 * i], p[2 * i + 1]) for i in range(nd)]
        else:
            # paddle style: pad applies to last len(p)//2 dims (reversed pairs, NCHW spatial)
            width = [(0, 0)] * nd
            npairs = len(p) // 2
            if data_format.startswith("NC") and nd >= 3 and npairs == nd - 2:
                for i in range(npairs):
                    width[2 + i] = (p[2 * i], p[2 * i + 1])
            else:
                for i in range(npairs):
                    width[nd - npairs + i] = (p[2 * i], p[2 * i + 1])
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
        kw = {"constant_values": value} if jmode == "constant" else {}
        return jnp.pad(a, width, mode=jmode, **kw)

    return apply_fn("pad", fn, x)


def repeat_interleave(x, repeats, axis=None, name=None):
    def fn(a, *r):
        rep = r[0] if r else repeats
        return jnp.repeat(a.reshape(-1) if axis is None else a, rep, axis=0 if axis is None else axis)

    if isinstance(repeats, Tensor):
        return apply_fn("repeat_interleave", fn, x, repeats)
    return apply_fn("repeat_interleave", fn, x)


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    a = np.asarray(_u(x))
    res = np.unique(a, return_index=return_index, return_inverse=return_inverse, return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    outs = [Tensor(jnp.asarray(r)) for r in res]
    return tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    a = np.asarray(_u(x)).reshape(-1) if axis is None else np.asarray(_u(x))
    vals = []
    counts = []
    inverse = np.zeros(a.shape[0], dtype=np.int64)
    for i, v in enumerate(a):
        if not vals or not np.array_equal(v, vals[-1]):
            vals.append(v)
            counts.append(1)
        else:
            counts[-1] += 1
        inverse[i] = len(vals) - 1
    outs = [Tensor(jnp.asarray(np.array(vals)))]
    if return_inverse:
        outs.append(Tensor(jnp.asarray(inverse)))
    if return_counts:
        outs.append(Tensor(jnp.asarray(np.array(counts))))
    return outs[0] if len(outs) == 1 else tuple(outs)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return tuple(Tensor(jnp.asarray(i)) for i in np.nonzero(np.asarray(_u(condition))))
    return apply_fn("where", lambda c, a, b: jnp.where(c, a, b), condition, x, y)


def nonzero(x, as_tuple=False):
    idx = np.nonzero(np.asarray(_u(x)))
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i)) for i in idx)
    return Tensor(jnp.asarray(np.stack(idx, axis=1)))


def as_real(x, name=None):
    return apply_fn("as_real", lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1), x)


def as_complex(x, name=None):
    return apply_fn("as_complex", lambda a: a[..., 0] + 1j * a[..., 1], x)


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return apply_fn("view_dtype", lambda a: a.view(dtype_mod.convert_dtype(shape_or_dtype)), x)


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def atleast_1d(*inputs, name=None):
    outs = [Tensor(jnp.atleast_1d(_u(i))) for i in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [Tensor(jnp.atleast_2d(_u(i))) for i in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [Tensor(jnp.atleast_3d(_u(i))) for i in inputs]
    return outs[0] if len(outs) == 1 else outs


def tensordot(x, y, axes=2, name=None):
    return apply_fn("tensordot", lambda a, b: jnp.tensordot(a, b, axes=axes), x, y)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    def fn(i):
        size = index_num // nshards
        lo, hi = shard_id * size, (shard_id + 1) * size
        return jnp.where((i >= lo) & (i < hi), i - lo, ignore_value)

    return apply_fn("shard_index", fn, input)
