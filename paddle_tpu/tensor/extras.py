"""Breadth completion of the tensor API — the long tail of
python/paddle/tensor functions not yet in math/linalg/manipulation/search.

Each op is a thin jax-traceable function dispatched through apply_fn (tape /
AMP / static-graph aware like every other op). Reference file cited per group.
"""

from __future__ import annotations

import math as _math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtype_mod
from ..core.op_registry import apply_fn
from ..core.tensor import Tensor, unwrap

__all__ = [
    "addmm", "block_diag", "cdist", "cholesky_inverse", "cumulative_trapezoid",
    "diagonal_scatter", "diff", "dist", "dsplit", "frexp", "gammainc",
    "gammaincc", "gammaln", "histogram_bin_edges", "histogramdd", "hsplit",
    "i0", "i0e", "i1", "i1e", "index_fill", "inverse", "is_complex",
    "is_empty", "is_floating_point", "is_integer", "is_tensor", "isin",
    "isneginf", "isposinf", "isreal", "ldexp", "logcumsumexp", "logit",
    "lu_unpack", "masked_scatter", "multigammaln", "nanquantile", "polygamma",
    "reduce_as", "renorm", "reverse", "select_scatter", "sgn", "signbit",
    "sinc", "slice_scatter", "svd_lowrank", "tensor_split", "top_p_sampling",
    "trapezoid", "unflatten", "unfold", "vander", "vsplit", "as_strided",
    "ormqr",
]


# ---- predicates (reference: tensor/attribute.py) ----

def is_tensor(x):
    return isinstance(x, Tensor)


def is_complex(x):
    return jnp.issubdtype(unwrap(x).dtype, jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(unwrap(x).dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(unwrap(x).dtype, jnp.integer)


def is_empty(x):
    return int(np.prod(unwrap(x).shape)) == 0


def isreal(x):
    return apply_fn("isreal", lambda a: jnp.isreal(a), x)


def isneginf(x):
    return apply_fn("isneginf", lambda a: jnp.isneginf(a), x)


def isposinf(x):
    return apply_fn("isposinf", lambda a: jnp.isposinf(a), x)


def signbit(x):
    return apply_fn("signbit", lambda a: jnp.signbit(a), x)


# ---- math (reference: tensor/math.py) ----

def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply_fn("addmm", lambda i, a, b: beta * i + alpha * (a @ b),
                    input, x, y)


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    def fn(a, b):
        d = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            return jnp.sqrt(jnp.maximum(jnp.sum(d * d, -1), 0.0))
        if p == float("inf"):
            return jnp.max(jnp.abs(d), -1)
        return jnp.sum(jnp.abs(d) ** p, -1) ** (1.0 / p)

    return apply_fn("cdist", fn, x, y)


def dist(x, y, p=2, name=None):
    def fn(a, b):
        d = (a - b).ravel()
        if p == 0:
            return jnp.count_nonzero(d).astype(a.dtype)
        if p == float("inf"):
            return jnp.max(jnp.abs(d))
        if p == float("-inf"):
            return jnp.min(jnp.abs(d))
        return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)

    return apply_fn("dist", fn, x, y)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    pre = unwrap(prepend) if prepend is not None else None
    app = unwrap(append) if append is not None else None
    return apply_fn("diff",
                    lambda a: jnp.diff(a, n=n, axis=axis, prepend=pre, append=app),
                    x)


def frexp(x, name=None):
    return apply_fn("frexp", lambda a: jnp.frexp(a), x)


def ldexp(x, y, name=None):
    return apply_fn("ldexp", lambda a, b: jnp.ldexp(a, b.astype(jnp.int32)), x, y)


def gammaln(x, name=None):
    return apply_fn("gammaln", lambda a: jax.scipy.special.gammaln(a), x)


def gammainc(x, y, name=None):
    return apply_fn("gammainc", lambda a, b: jax.scipy.special.gammainc(a, b), x, y)


def gammaincc(x, y, name=None):
    return apply_fn("gammaincc", lambda a, b: jax.scipy.special.gammaincc(a, b), x, y)


def multigammaln(x, p, name=None):
    return apply_fn("multigammaln",
                    lambda a: jax.scipy.special.multigammaln(a, int(p)), x)


def polygamma(x, n, name=None):
    return apply_fn("polygamma",
                    lambda a: jax.scipy.special.polygamma(int(n), a), x)


def i0(x, name=None):
    return apply_fn("i0", lambda a: jax.scipy.special.i0(a), x)


def i0e(x, name=None):
    return apply_fn("i0e", lambda a: jax.scipy.special.i0e(a), x)


def i1(x, name=None):
    return apply_fn("i1", lambda a: jax.scipy.special.i1(a), x)


def i1e(x, name=None):
    return apply_fn("i1e", lambda a: jax.scipy.special.i1e(a), x)


def logit(x, eps=None, name=None):
    def fn(a):
        if eps is not None:
            a = jnp.clip(a, eps, 1.0 - eps)
        return jax.scipy.special.logit(a)

    return apply_fn("logit", fn, x)


def logcumsumexp(x, axis=None, dtype=None, name=None):
    def fn(a):
        if axis is None:
            a = a.ravel()
            ax = 0
        else:
            ax = axis
        return jax.lax.associative_scan(jnp.logaddexp, a, axis=ax)

    return apply_fn("logcumsumexp", fn, x)


def sgn(x, name=None):
    def fn(a):
        if jnp.issubdtype(a.dtype, jnp.complexfloating):
            mag = jnp.abs(a)
            return jnp.where(mag == 0, 0, a / jnp.maximum(mag, 1e-38))
        return jnp.sign(a)

    return apply_fn("sgn", fn, x)


def sinc(x, name=None):
    return apply_fn("sinc", lambda a: jnp.sinc(a), x)


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return apply_fn("isin", lambda a, t: jnp.isin(a, t, invert=invert), x, test_x)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    xv = unwrap(x) if x is not None else None

    def fn(a):
        return jnp.trapezoid(a, x=xv, dx=dx if dx is not None else 1.0, axis=axis)

    return apply_fn("trapezoid", fn, y)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    xv = unwrap(x) if x is not None else None

    def fn(a):
        d = (jnp.diff(xv, axis=axis) if xv is not None
             else (dx if dx is not None else 1.0))
        sl1 = [slice(None)] * a.ndim
        sl2 = [slice(None)] * a.ndim
        sl1[axis] = slice(1, None)
        sl2[axis] = slice(None, -1)
        avg = (a[tuple(sl1)] + a[tuple(sl2)]) / 2.0
        return jnp.cumsum(avg * d, axis=axis)

    return apply_fn("cumulative_trapezoid", fn, y)


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    qv = unwrap(q) if isinstance(q, Tensor) else q
    return apply_fn(
        "nanquantile",
        lambda a: jnp.nanquantile(a, qv, axis=axis, keepdims=keepdim,
                                  method=interpolation), x)


def reduce_as(x, target, name=None):
    """Sum-reduce x to target's shape (reference: tensor/math.py reduce_as)."""

    def fn(a, t):
        extra = a.ndim - t.ndim
        axes = tuple(range(extra)) + tuple(
            i + extra for i, s in enumerate(t.shape) if s == 1 and a.shape[i + extra] != 1)
        out = jnp.sum(a, axis=axes, keepdims=False)
        return out.reshape(t.shape)

    return apply_fn("reduce_as", fn, x, target)


def renorm(x, p, axis, max_norm, name=None):
    def fn(a):
        axes = tuple(i for i in range(a.ndim) if i != axis % a.ndim)
        norms = jnp.sum(jnp.abs(a) ** p, axis=axes, keepdims=True) ** (1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / jnp.maximum(norms, 1e-12), 1.0)
        return a * factor

    return apply_fn("renorm", fn, x)


def vander(x, n=None, increasing=False, name=None):
    return apply_fn("vander",
                    lambda a: jnp.vander(a, N=n, increasing=increasing), x)


# ---- linalg (reference: tensor/linalg.py) ----

def inverse(x, name=None):
    return apply_fn("inverse", lambda a: jnp.linalg.inv(a), x)


def cholesky_inverse(x, upper=False, name=None):
    def fn(l):
        u = l.T if not upper else l
        inv_u = jax.scipy.linalg.solve_triangular(
            u, jnp.eye(u.shape[-1], dtype=u.dtype), lower=False)
        return inv_u @ inv_u.T

    return apply_fn("cholesky_inverse", fn, x)


def block_diag(inputs, name=None):
    # pass the ORIGINAL tensors through apply_fn so autograd links survive
    args = [i if isinstance(i, Tensor) else Tensor(np.asarray(i))
            for i in inputs]
    return apply_fn("block_diag", lambda *a: jax.scipy.linalg.block_diag(*a),
                    *args)


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack combined LU factor + pivots (reference: tensor/linalg.py lu_unpack)."""

    def fn(lu, piv):
        m, n = lu.shape[-2], lu.shape[-1]
        k = min(m, n)
        l = jnp.tril(lu[..., :, :k], -1) + jnp.eye(m, k, dtype=lu.dtype)
        u = jnp.triu(lu[..., :k, :])
        # pivots (1-based sequential swaps) -> permutation, batched
        batch = piv.shape[:-1]
        perm = jnp.broadcast_to(jnp.arange(m), batch + (m,))
        for i in range(piv.shape[-1]):
            j = (piv[..., i] - 1)[..., None].astype(jnp.int32)
            pi = perm[..., i: i + 1]
            pj = jnp.take_along_axis(perm, j, -1)
            perm = jnp.put_along_axis(perm, j, pi, -1, inplace=False)
            perm = perm.at[..., i].set(pj[..., 0])
        p = jnp.swapaxes(jnp.eye(m, dtype=lu.dtype)[perm], -1, -2)
        return p, l, u

    return apply_fn("lu_unpack", fn, lu_data, lu_pivots)


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized low-rank SVD (reference: tensor/linalg.py svd_lowrank)."""
    from ..framework.random import next_key

    def fn(a):
        m, n = a.shape[-2], a.shape[-1]
        r = min(q, m, n)
        g = jax.random.normal(next_key(), a.shape[:-2] + (n, r), a.dtype)
        y = a @ g
        for _ in range(niter):
            y = a @ (a.swapaxes(-1, -2) @ y)
        qmat, _ = jnp.linalg.qr(y)
        b = qmat.swapaxes(-1, -2) @ a
        u, s, vh = jnp.linalg.svd(b, full_matrices=False)
        return qmat @ u, s, vh.swapaxes(-1, -2)

    return apply_fn("svd_lowrank", fn, x)


def ormqr(x, tau, y, left=True, transpose=False, name=None):
    """Multiply by Q from a QR factorization's householder reflectors
    (reference: tensor/linalg.py ormqr)."""

    def fn(a, t, other):
        # pad reflectors/taus to m so householder_product yields the FULL
        # m x m Q (extra tau=0 reflectors are identities)
        m = a.shape[-2]
        if a.shape[-1] < m:
            pad = [(0, 0)] * (a.ndim - 1) + [(0, m - a.shape[-1])]
            a = jnp.pad(a, pad)
        if t.shape[-1] < m:
            t = jnp.pad(t, [(0, 0)] * (t.ndim - 1) + [(0, m - t.shape[-1])])
        q = jax.lax.linalg.householder_product(a, t)
        qm = q.swapaxes(-1, -2) if transpose else q
        return qm @ other if left else other @ qm

    return apply_fn("ormqr", fn, x, tau, y)


# ---- manipulation (reference: tensor/manipulation.py) ----

def _split_helper(op_name, axis):
    def f(x, num_or_indices, name=None):
        def fn(a):
            if isinstance(num_or_indices, int):
                return tuple(jnp.split(a, num_or_indices, axis=axis))
            return tuple(jnp.split(a, list(num_or_indices), axis=axis))

        return apply_fn(op_name, fn, x)

    f.__name__ = op_name
    return f


hsplit = _split_helper("hsplit", 1)
vsplit = _split_helper("vsplit", 0)
dsplit = _split_helper("dsplit", 2)


def tensor_split(x, num_or_indices, axis=0, name=None):
    def fn(a):
        return tuple(jnp.array_split(a, num_or_indices
                                     if isinstance(num_or_indices, int)
                                     else list(num_or_indices), axis=axis))

    return apply_fn("tensor_split", fn, x)


def reverse(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return apply_fn("reverse", lambda a: jnp.flip(a, axis=tuple(axes)), x)


def unflatten(x, axis, shape, name=None):
    shp = tuple(int(unwrap(s)) for s in shape)

    def fn(a):
        ax = axis % a.ndim
        return a.reshape(a.shape[:ax] + shp + a.shape[ax + 1:])

    return apply_fn("unflatten", fn, x)


def unfold(x, axis, size, step, name=None):
    """Sliding windows along axis -> appended window dim (reference:
    tensor/manipulation.py unfold; torch.Tensor.unfold semantics)."""

    def fn(a):
        ax = axis % a.ndim
        n = a.shape[ax]
        num = (n - size) // step + 1
        starts = jnp.arange(num) * step
        idx = starts[:, None] + jnp.arange(size)[None, :]
        out = jnp.take(a, idx.reshape(-1), axis=ax)
        out = out.reshape(a.shape[:ax] + (num, size) + a.shape[ax + 1:])
        return jnp.moveaxis(out, ax + 1, -1)

    return apply_fn("unfold", fn, x)


def as_strided(x, shape, stride, offset=0, name=None):
    """Strided view materialized via gather (XLA has no aliasing views)."""
    shape = tuple(int(s) for s in shape)
    stride = tuple(int(s) for s in stride)

    def fn(a):
        flat = a.ravel()
        if not shape:
            return flat[offset]
        mesh = jnp.meshgrid(*[jnp.arange(s) for s in shape], indexing="ij")
        lin = offset
        for g, st in zip(mesh, stride):
            lin = lin + g * st
        return flat[lin]

    return apply_fn("as_strided", fn, x)


def index_fill(x, index, axis, value, name=None):
    val = unwrap(value) if isinstance(value, Tensor) else value

    def fn(a, idx):
        moved = jnp.moveaxis(a, axis, 0)
        moved = moved.at[idx].set(val)
        return jnp.moveaxis(moved, 0, axis)

    return apply_fn("index_fill", fn, x, index)


def masked_scatter(x, mask, value, name=None):
    def fn(a, m, v):
        mb = jnp.broadcast_to(m, a.shape).ravel()
        # position among True entries for each element
        pos = jnp.cumsum(mb) - 1
        src = v.ravel()
        gathered = src[jnp.clip(pos, 0, src.shape[0] - 1)]
        return jnp.where(mb, gathered, a.ravel()).reshape(a.shape)

    return apply_fn("masked_scatter", fn, x, mask, value)


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    def fn(a, b):
        ax1, ax2 = axis1 % a.ndim, axis2 % a.ndim
        moved = jnp.moveaxis(a, (ax1, ax2), (-2, -1))
        m, n = moved.shape[-2], moved.shape[-1]
        rows = jnp.arange(max(m, n))
        if offset >= 0:
            r, c = rows[: min(m, n - offset)], rows[: min(m, n - offset)] + offset
        else:
            r, c = rows[: min(m + offset, n)] - offset, rows[: min(m + offset, n)]
        moved = moved.at[..., r, c].set(b)
        return jnp.moveaxis(moved, (-2, -1), (ax1, ax2))

    return apply_fn("diagonal_scatter", fn, x, y)


def select_scatter(x, values, axis, index, name=None):
    def fn(a, v):
        moved = jnp.moveaxis(a, axis, 0)
        moved = moved.at[index].set(v)
        return jnp.moveaxis(moved, 0, axis)

    return apply_fn("select_scatter", fn, x, values)


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    def fn(a, v):
        sl = [slice(None)] * a.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            sl[ax] = slice(st, en, sd)
        return a.at[tuple(sl)].set(v)

    return apply_fn("slice_scatter", fn, x, value)


# ---- histogram (reference: tensor/linalg.py histogram*) ----

def histogram_bin_edges(x, bins=100, min=0.0, max=0.0, name=None):
    def fn(a):
        lo, hi = (jnp.min(a), jnp.max(a)) if min == max == 0.0 else (min, max)
        return jnp.linspace(lo, hi, bins + 1).astype(jnp.float32)

    return apply_fn("histogram_bin_edges", fn, x)


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    wv = unwrap(weights) if weights is not None else None
    # reference API: ``ranges`` is a FLAT [min0, max0, min1, max1, ...] list
    # (tensor/linalg.py histogramdd); jnp wants (min, max) pairs — caught by
    # the round-5 numeric sweep
    rng_pairs = None
    if ranges is not None:
        flat = [float(v) for v in ranges]
        if len(flat) % 2:
            raise ValueError("ranges must hold min/max pairs, got odd length")
        rng_pairs = [(flat[i], flat[i + 1]) for i in range(0, len(flat), 2)]

    def fn(a):
        return jnp.histogramdd(a, bins=bins, range=rng_pairs, density=density,
                               weights=wv)

    return apply_fn("histogramdd", fn, x)


# ---- sampling (reference: tensor/search.py top_p_sampling) ----

def top_p_sampling(x, ps, threshold=None, seed=None, name=None):
    """Nucleus sampling over the last axis; ``ps`` may be a scalar or a
    per-batch tensor [batch]. Returns (values, indices)."""
    from ..framework.random import next_key

    if isinstance(ps, (float, int)):
        pv = jnp.asarray(float(ps), jnp.float32)
    else:
        pv = unwrap(ps).astype(jnp.float32)

    def fn(logits):
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        sort_idx = jnp.argsort(-probs, axis=-1)
        sorted_p = jnp.take_along_axis(probs, sort_idx, axis=-1)
        cum = jnp.cumsum(sorted_p, axis=-1)
        p_b = pv if pv.ndim == 0 else pv.reshape(pv.shape + (1,) * (logits.ndim - pv.ndim))
        keep = cum - sorted_p <= p_b  # keep tokens until cumulative mass > p
        filtered = jnp.where(keep, sorted_p, 0.0)
        filtered = filtered / jnp.sum(filtered, axis=-1, keepdims=True)
        key = jax.random.key(int(seed)) if seed is not None else next_key()
        choice = jax.random.categorical(key, jnp.log(jnp.maximum(filtered, 1e-38)),
                                        axis=-1)
        idx = jnp.take_along_axis(sort_idx, choice[..., None], axis=-1)
        val = jnp.take_along_axis(probs, idx, axis=-1)
        return val, idx

    return apply_fn("top_p_sampling", fn, x)
