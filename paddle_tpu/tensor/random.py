"""Random ops (reference: python/paddle/tensor/random.py) over the global PRNG state."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import dtype as dtype_mod
from ..core.tensor import Tensor, unwrap
from ..framework.random import next_key


def _dt(dtype, default=None):
    return dtype_mod.convert_dtype(dtype) or default or dtype_mod.get_default_dtype()


def _shape(shape):
    if isinstance(shape, Tensor):
        import numpy as np

        return tuple(int(v) for v in np.asarray(shape._data))
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(unwrap(s)) for s in shape)


def rand(shape, dtype=None, name=None):
    return Tensor(jax.random.uniform(next_key(), _shape(shape), _dt(dtype)))


def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(next_key(), _shape(shape), _dt(dtype)))


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m, s = unwrap(mean), unwrap(std)
        shp = jnp.broadcast_shapes(getattr(m, "shape", ()), getattr(s, "shape", ()))
        return Tensor(jax.random.normal(next_key(), shp) * s + m)
    return Tensor(jax.random.normal(next_key(), _shape(shape or [1])) * std + mean)


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    return Tensor(jax.random.normal(next_key(), _shape(shape), _dt(dtype)) * std + mean)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    return Tensor(jax.random.uniform(next_key(), _shape(shape), _dt(dtype), minval=unwrap(min), maxval=unwrap(max)))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    x._data = jax.random.uniform(next_key(), tuple(x.shape), x.dtype, minval=min, maxval=max)
    return x


def randint(low=0, high=None, shape=[1], dtype=None, name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(next_key(), _shape(shape), int(low), int(high), _dt(dtype, jnp.int64)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(next_key(), tuple(x.shape), int(low), int(high), _dt(dtype, unwrap(x).dtype)))


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(next_key(), int(n)).astype(_dt(dtype, jnp.int64)))


def multinomial(x, num_samples=1, replacement=False, name=None):
    a = unwrap(x)
    logits = jnp.log(jnp.maximum(a, 1e-30))
    if replacement:
        out = jax.random.categorical(next_key(), logits, axis=-1, shape=(num_samples,) + a.shape[:-1])
        out = jnp.moveaxis(out, 0, -1)
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(next_key(), a.shape)
        out = jnp.argsort(-(logits + g), axis=-1)[..., :num_samples]
    return Tensor(out.astype(jnp.int64))


def bernoulli(x, name=None):
    a = unwrap(x)
    return Tensor(jax.random.bernoulli(next_key(), a).astype(a.dtype))


def bernoulli_(x, p=0.5, name=None):
    x._data = jax.random.bernoulli(next_key(), p, tuple(x.shape)).astype(x.dtype)
    return x


def poisson(x, name=None):
    a = unwrap(x)
    return Tensor(jax.random.poisson(next_key(), a).astype(a.dtype))


def exponential_(x, lam=1.0, name=None):
    x._data = (jax.random.exponential(next_key(), tuple(x.shape), x.dtype) / lam).astype(x.dtype)
    return x


def binomial(count, prob, name=None):
    c, p = unwrap(count), unwrap(prob)
    return Tensor(jax.random.binomial(next_key(), c, p).astype(jnp.int64))


def normal_(x, mean=0.0, std=1.0, name=None):
    x._data = (jax.random.normal(next_key(), tuple(x.shape), x.dtype) * std + mean).astype(x.dtype)
    return x


def rand_like(x, dtype=None, name=None):
    return Tensor(jax.random.uniform(next_key(), tuple(x.shape), _dt(dtype, unwrap(x).dtype)))


def randn_like(x, dtype=None, name=None):
    return Tensor(jax.random.normal(next_key(), tuple(x.shape), _dt(dtype, unwrap(x).dtype)))
