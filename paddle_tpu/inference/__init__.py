"""paddle_tpu.inference — the serving/deployment path.

Parity anchors: the reference's AnalysisPredictor
(/root/reference/paddle/fluid/inference/api/analysis_predictor.cc:1657 Run,
:1241 PrepareExecutor, :1171 OptimizeInferenceProgram) and its Python surface
(python/paddle/inference/__init__.py: Config / create_predictor / Predictor
with get_input_names / get_input_handle / run / get_output_handle).

TPU-native redesign: the reference's analysis passes (IR fusion, TRT subgraph
capture, mixed precision rewrite) collapse into XLA AOT compilation of an
exported StableHLO artifact:
  - ``paddle.jit.save(layer, path, input_spec=...)`` produces ``path.pdmodel``
    (a serialized ``jax.export`` StableHLO module — the portable, C++-loadable
    deployment format: any PJRT runtime can load it, which is the analogue of
    the reference's C API / fluid/inference/capi_exp) and ``path.pdiparams``.
  - ``create_predictor(Config(path))`` deserializes once and AOT-compiles per
    input-shape signature; repeated ``run()`` calls hit the compiled
    executable with zero Python-graph overhead.
  - mixed-precision serving = bf16 weight cast at load (Config.enable_bf16),
    the analogue of convert_to_mixed_precision.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["Config", "Predictor", "create_predictor", "PredictorTensor"]


class Config:
    """Inference config (reference: paddle/fluid/inference/api/paddle_analysis_config.h).

    GPU/TRT/MKLDNN toggles are accepted for API compatibility and ignored —
    device placement is XLA's concern; `enable_bf16()` is the mixed-precision
    switch that matters on TPU.
    """

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self.model_path = prog_file
        self.params_file = params_file
        self._bf16 = False
        self._memory_optim = True
        self._ir_optim = True
        self._donate_inputs = False

    # --- parity switches ---
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        pass  # device is XLA's concern

    def disable_gpu(self):
        pass

    def enable_memory_optim(self, x: bool = True):
        self._memory_optim = x

    def switch_ir_optim(self, x: bool = True):
        self._ir_optim = x

    def enable_bf16(self, x: bool = True):
        """Serve with bfloat16 weights (reference: convert_to_mixed_precision /
        enable_mkldnn_bfloat16)."""
        self._bf16 = x

    def enable_donate_inputs(self, x: bool = True):
        """Donate the per-call input buffers to XLA (the weights are NOT
        donated — they are reused every ``run``). Each ``run`` uploads
        fresh host arrays anyway, so donation lets the runtime alias them
        for outputs instead of holding both live. Off by default for API
        parity; the PT-COST donation audit (docs/STATIC_ANALYSIS.md)
        flags carry buffers, not per-call inputs, so leaving this off is
        a memory choice, not a lint finding."""
        self._donate_inputs = x

    def set_cpu_math_library_num_threads(self, n: int):
        pass

    def enable_tensorrt_engine(self, *a, **k):
        pass  # TRT has no TPU analogue; XLA AOT covers it

    def summary(self) -> str:
        return (f"Config(model={self.model_path}, bf16={self._bf16}, "
                f"memory_optim={self._memory_optim})")


class PredictorTensor:
    """Input/output handle (reference: ZeroCopyTensor, analysis_predictor.cc
    GetInputTensor/GetOutputTensor). copy_from_cpu/copy_to_cpu keep the
    zero-copy API shape; on TPU the transfer happens at run()."""

    def __init__(self, name: str, shape=None, dtype=None):
        self.name = name
        self._shape = list(shape) if shape else None
        self._dtype = dtype
        self._value: Optional[np.ndarray] = None

    def reshape(self, shape):
        self._shape = list(shape)

    def copy_from_cpu(self, arr: np.ndarray):
        self._value = np.asarray(arr)
        self._shape = list(self._value.shape)

    def copy_to_cpu(self) -> np.ndarray:
        if self._value is None:
            raise RuntimeError(f"output '{self.name}' not computed — call run()")
        return np.asarray(self._value)

    def shape(self):
        return self._shape

    def type(self):
        return self._dtype


class Predictor:
    """AOT-compiled predictor over a jit.save artifact or a live Layer."""

    def __init__(self, config: Config):
        import jax

        from ..framework import io as fio

        self._config = config

        if config.model_path is None:
            raise ValueError("Config needs a model path prefix (jit.save output)")
        from jax import export as jexport

        with open(config.model_path + ".pdmodel", "rb") as f:
            self._exported = jexport.deserialize(f.read())
        params_path = config.params_file or config.model_path + ".pdiparams"
        state = fio.load(params_path)
        from ..core.tensor import unwrap

        self._state = [np.asarray(unwrap(v)) for v in state.values()]
        self._call = self._exported.call
        if config._donate_inputs and not config._bf16:
            # honor the (previously write-only) donation knob: inputs are
            # fresh uploads every run(), safe to donate; state is carried
            # across calls and must NOT be (donating it would delete the
            # weights after the first call). The bf16 path composes its
            # own jit below (same donate_argnums).
            exported0 = self._exported
            self._call = jax.jit(
                lambda state, ins: exported0.call(state, ins),
                donate_argnums=(1,))
        if config._bf16:
            # store weights bf16 (half the HBM), upcast at the call boundary —
            # XLA folds the cast into the first consumer, so matmuls read bf16
            import jax.numpy as jnp

            orig_dtypes = [a.dtype for a in self._state]
            self._state = [
                jnp.asarray(a, jnp.bfloat16) if a.dtype == np.float32 else a
                for a in self._state]
            exported = self._exported

            def call_bf16(state, ins):
                state = [s.astype(d) if s.dtype != d else s
                         for s, d in zip(state, orig_dtypes)]
                return exported.call(state, ins)

            self._call = jax.jit(
                call_bf16,
                donate_argnums=(1,) if config._donate_inputs else ())
        # input signature from the exported module: (state_list, input_tuple)
        in_avals = self._exported.in_avals
        self._n_state = len(self._state)
        self._input_avals = list(in_avals[self._n_state:])
        self._inputs = [
            PredictorTensor(f"x{i}", a.shape, str(a.dtype))
            for i, a in enumerate(self._input_avals)]
        self._outputs: List[PredictorTensor] = [
            PredictorTensor(f"out{i}", a.shape, str(a.dtype))
            for i, a in enumerate(self._exported.out_avals)]

    # --- handle API (reference: analysis_predictor.cc GetInputNames/Run) ---
    def get_input_names(self) -> List[str]:
        return [t.name for t in self._inputs]

    def get_output_names(self) -> List[str]:
        return [t.name for t in self._outputs]

    def get_input_handle(self, name: str) -> PredictorTensor:
        return next(t for t in self._inputs if t.name == name)

    def get_output_handle(self, name: str) -> PredictorTensor:
        return next(t for t in self._outputs if t.name == name)

    def run(self, inputs: Optional[Sequence[np.ndarray]] = None):
        """Execute. With ``inputs`` given, returns outputs directly (list of
        np arrays); otherwise uses the copy_from_cpu'd handles."""
        import jax

        if inputs is not None:
            arrs = [np.asarray(a) for a in inputs]
        else:
            missing = [t.name for t in self._inputs if t._value is None]
            if missing:
                raise RuntimeError(f"inputs not set: {missing}")
            arrs = [t._value for t in self._inputs]
        if len(arrs) != len(self._input_avals):
            raise ValueError(
                f"expected {len(self._input_avals)} inputs, got {len(arrs)}")
        for a, aval in zip(arrs, self._input_avals):
            if tuple(a.shape) != tuple(aval.shape):
                raise ValueError(
                    f"input shape {a.shape} != exported {tuple(aval.shape)} — "
                    f"export with the serving shape (or a symbolic batch dim)")
        outs = self._call(self._state, tuple(arrs))
        out_list = list(outs) if isinstance(outs, (tuple, list)) else [outs]
        for t, o in zip(self._outputs, out_list):
            t._value = np.asarray(o)
        if inputs is not None:
            return [np.asarray(o) for o in out_list]
        return None

    def clear_intermediate_tensor(self):
        pass

    def try_shrink_memory(self):
        pass  # XLA owns the buffers; nothing framework-side to free


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
