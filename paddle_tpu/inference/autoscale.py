"""SLO-pressure autoscaler: attainment windows in, fleet actions out.

The observability stack (docs/OBSERVABILITY.md) can now measure what
production would see — windowed SLO attainment and goodput
(observability/slo.py) under open-loop replay (observability/workload.py).
This module closes the loop (ROADMAP item 5): :class:`SLOAutoscaler` reads
the :class:`~paddle_tpu.observability.slo.SLOMonitor` windows and drives
the PR 6 fleet machinery —

- **scale up** (PT-ASC-001): ``up_after`` consecutive windows below
  ``target_attainment`` add a replica via
  :meth:`~paddle_tpu.inference.fleet.FleetRouter.add_replica` (the same
  supervisor/journal factory path every other replica was built through).
- **brownout** (PT-ASC-002): at ``max_replicas`` the only lever left is
  degradation — :meth:`FleetRouter.force_brownout` sheds sheddable
  priority classes at submit until attainment recovers (the PR 6
  hysteretic brownout, engaged by the controller instead of queue depth).
- **scale down** (PT-ASC-003): ``down_after`` consecutive windows at or
  above ``headroom_attainment`` first release a forced brownout, then
  retire the least-loaded replica via
  :meth:`FleetRouter.retire_replica` (drain-then-remove: still-queued
  work migrates, in-flight work finishes in place — nothing is lost to a
  scale-in).

Hysteresis everywhere: consecutive-window counters gate every transition
and ``cooldown_windows`` quiet periods follow every action, so one noisy
window can neither flap replicas nor oscillate brownout. Windows with
fewer than ``min_window_requests`` finished requests are no evidence and
leave the counters untouched.

Every decision is stamped as a trace event (``autoscale`` instants in the
engine lane), appended to :attr:`decisions`, and counted in the metrics
registry (``pt_autoscaler_*`` families) — a scale action you cannot see
in the trace/scrape did not happen, operationally speaking.

The controller is deliberately thread-free: :meth:`tick` is called at
window boundaries by whoever owns the clock (the
:class:`~paddle_tpu.observability.workload.ReplayDriver` in replay, an
operator loop in production).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

__all__ = ["AutoscaleConfig", "SLOAutoscaler"]


@dataclasses.dataclass
class AutoscaleConfig:
    """Controller knobs (module docstring for the state machine).

    ``target_attainment=None`` inherits the monitor's
    ``SLOConfig.target_attainment`` — one contract, judged in one place."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_attainment: Optional[float] = None
    headroom_attainment: float = 0.98
    up_after: int = 2
    down_after: int = 4
    cooldown_windows: int = 1
    min_window_requests: int = 1


class SLOAutoscaler:
    """>>> scaler = SLOAutoscaler(fleet, monitor, AutoscaleConfig(
    ...     min_replicas=1, max_replicas=3))
    >>> # at every SLO window boundary:
    >>> decision = scaler.tick()      # None | scale_up | scale_down |
    ...                               # brownout | brownout_exit

    ``enabled=False`` is the control arm (tools/traffic_replay.py): the
    ticks still read the windows and keep counters, but no fleet action is
    taken — under the same seeded burst schedule the attainment difference
    between the arms is the autoscaler's measured worth."""

    def __init__(self, router, monitor, config: Optional[AutoscaleConfig]
                 = None, registry=None, tracer=None, enabled: bool = True):
        self.router = router
        self.monitor = monitor
        self.config = config or AutoscaleConfig()
        self.tracer = tracer
        self.enabled = bool(enabled)
        self.decisions: List[dict] = []
        self.stats = {"ticks": 0, "scale_ups": 0, "scale_downs": 0,
                      "brownouts": 0, "brownout_exits": 0,
                      "pressured_windows": 0, "headroom_windows": 0}
        self._low = 0          # consecutive windows below target
        self._high = 0         # consecutive windows at/above headroom
        self._cooldown = 0
        self._forced_brownout = False
        self._c_up = self._c_down = self._c_brown = self._g_replicas = None
        if registry is not None:
            self._c_up = registry.counter(
                "pt_autoscaler_scale_ups_total",
                "replicas added on SLO-attainment shortfall")
            self._c_down = registry.counter(
                "pt_autoscaler_scale_downs_total",
                "replicas retired on sustained SLO headroom")
            self._c_brown = registry.counter(
                "pt_autoscaler_brownouts_total",
                "forced fleet brownouts at max replicas")
            self._g_replicas = registry.gauge(
                "pt_autoscaler_replicas",
                "replicas the autoscaler currently counts as serving")

    # -- internals ---------------------------------------------------------
    def _target(self) -> float:
        if self.config.target_attainment is not None:
            return self.config.target_attainment
        return self.monitor.config.target_attainment

    def _alive(self) -> int:
        from .fleet import ReplicaState

        return sum(1 for r in self.router.replicas
                   if r.state in (ReplicaState.ALIVE,
                                  ReplicaState.DRAINING))

    def _decide(self, action: str, window: Optional[dict],
                detail: str) -> str:
        replicas = self._alive()
        if self._g_replicas is not None:
            self._g_replicas.set(replicas)      # post-action truth
        rec = {"tick": self.stats["ticks"], "action": action,
               "replicas": replicas,
               "window": None if window is None else window.get("window"),
               "attainment": (None if window is None
                              else window.get("attainment")),
               "detail": detail}
        self.decisions.append(rec)
        key = {"scale_up": "scale_ups", "scale_down": "scale_downs",
               "brownout": "brownouts",
               "brownout_exit": "brownout_exits"}[action]
        self.stats[key] += 1
        # brownout_exit deliberately has no counter: pt_autoscaler_
        # brownouts_total counts ENTRIES only
        counter = {"scale_up": self._c_up, "scale_down": self._c_down,
                   "brownout": self._c_brown}.get(action)
        if counter is not None:
            counter.inc()
        if self.tracer is not None:
            self.tracer.instant("autoscale", None, None, action=action,
                                replicas=replicas, detail=detail,
                                attainment=rec["attainment"])
        self._cooldown = self.config.cooldown_windows
        self._low = self._high = 0
        return action

    # -- the control step --------------------------------------------------
    def tick(self, window: Optional[dict] = None) -> Optional[str]:
        """One control step: judge the latest finalized window, maybe act.
        Returns the decision name (or None). Call at window boundaries,
        AFTER ``monitor.roll_window`` (the ReplayDriver does both)."""
        cfg = self.config
        self.stats["ticks"] += 1
        if window is None:
            window = self.monitor.last_window()
        if self._g_replicas is not None:
            self._g_replicas.set(self._alive())
        if window is None:
            return None
        attain = window.get("attainment")
        finished = window.get("finished", 0)
        if attain is None or finished < cfg.min_window_requests:
            return None          # no evidence: counters hold, no decay
        if self._forced_brownout:
            # the forced brownout's OWN sheds count as unmet requests, so
            # overall attainment is capped at (1 - sheddable share) and
            # could never reach headroom — recovery must be judged on the
            # traffic that was actually served
            served = window.get("served_attainment")
            if served is not None:
                attain = served
        target = self._target()
        if attain < target:
            self._low += 1
            self._high = 0
            self.stats["pressured_windows"] += 1
        elif attain >= cfg.headroom_attainment:
            self._high += 1
            self._low = 0
            self.stats["headroom_windows"] += 1
        else:
            self._low = self._high = 0
        if self._cooldown > 0:
            self._cooldown -= 1
            return None
        if not self.enabled:
            return None
        if self._low >= cfg.up_after:
            alive = self._alive()
            if alive < cfg.max_replicas:
                idx = self.router.add_replica()
                return self._decide(
                    "scale_up", window,
                    f"attainment {attain:.3f} < {target:.3f} for "
                    f"{cfg.up_after} window(s) — replica {idx} added "
                    f"({alive} -> {alive + 1})")
            if not self._forced_brownout:
                self._forced_brownout = True
                self.router.force_brownout(True)
                return self._decide(
                    "brownout", window,
                    f"attainment {attain:.3f} < {target:.3f} at max "
                    f"replicas ({cfg.max_replicas}) — fleet brownout "
                    "forced (shedding sheddable priorities at submit)")
            self._low = 0        # already maximally degraded: hold state
            return None
        if self._high >= cfg.down_after:
            if self._forced_brownout:
                self._forced_brownout = False
                self.router.force_brownout(False)
                return self._decide(
                    "brownout_exit", window,
                    f"attainment {attain:.3f} >= "
                    f"{cfg.headroom_attainment:.3f} for {cfg.down_after} "
                    "window(s) — forced brownout released")
            alive = self._alive()
            if alive > cfg.min_replicas:
                idx = self._pick_retire()
                if idx is not None and self.router.retire_replica(idx):
                    return self._decide(
                        "scale_down", window,
                        f"attainment {attain:.3f} >= "
                        f"{cfg.headroom_attainment:.3f} for "
                        f"{cfg.down_after} window(s) — replica {idx} "
                        f"retiring ({alive} -> {alive - 1})")
            self._high = 0       # nothing to shed: hold at floor
        return None

    def _pick_retire(self) -> Optional[int]:
        """Least-loaded ALIVE replica (highest index tie-break — the
        autoscaler retires newest-first so the original fleet shape is
        what survives a scale cycle)."""
        from .fleet import ReplicaState

        alive = [r for r in self.router.replicas
                 if r.state == ReplicaState.ALIVE]
        if len(alive) <= 1:
            return None
        return min(alive, key=lambda r: (r.sup.load(), -r.idx)).idx

    def report(self) -> dict:
        return {"config": dataclasses.asdict(self.config),
                "enabled": self.enabled,
                "stats": dict(self.stats),
                "forced_brownout": self._forced_brownout,
                "replicas": self._alive(),
                "decisions": list(self.decisions)}
