"""Continuous-batching serving engine over paged KV caches.

The TPU-native counterpart of the reference's serving stack around
block_multihead_attention (python/paddle/incubate/nn/functional/
block_multihead_attention.py over block_multi_head_attention_kernel.cu)
plus its sampling op (python/paddle/tensor/search.py:1362 top_p_sampling):
a fixed pool of KV pages + per-slot block tables, requests admitted into
free slots as others finish — decode compute and cache memory are bounded
by the pool, not by the longest request.

Design (one jitted program per phase, static shapes):
  - ``max_batch`` slots share per-layer page pools sized
    ``max_batch * ceil(max_len / page)`` pages (``_init_paged_caches``).
  - ADMIT: a new request prefills ITS slot only. With ``prompt_buckets`` the
    prompt is right-padded to the nearest bucket (one compilation per bucket):
    the padded chunk fills the cache, then the last REAL token is re-stepped
    at its true position so the first sampled token sees exactly the real
    prompt — pad cache entries sit beyond the attended window and are
    overwritten as decode advances.
  - STEP: ONE fused ``lax.scan`` of ``paged_token_step`` advances EVERY
    active slot up to ``block_size`` tokens per host round-trip — per-row
    positions flow into the paged decode kernel; the host syncs once per
    block, not once per token. Inactive slots run on a parked dummy row
    whose output is ignored.
  - SAMPLE: per-request temperature / top-p / top-k / seed, applied
    row-vectorized inside the fused step. Keys are stateless:
    ``fold_in(key(seed), token_position)`` — reproducible per request and
    independent of batching/arrival order. temperature==0 is greedy.
  - FINISH: eos or max_new_tokens frees the slot; its pages are reused by
    the next admission (tables are per-slot, so no copying). Tokens decoded
    past an eos inside a block are discarded on the host (bounded waste,
    the standard continuous-batching speculation tradeoff).

Numerics: with default greedy sampling the engine is EXACTLY equal to
``generate(cache_impl='paged')`` (verified token-for-token on the real chip);
versus the dense-cache generate it matches exactly in fp32 (CPU tests) while
bf16-on-TPU tokens may diverge at softmax near-ties between the two attention
kernels — the standard cross-kernel serving caveat.
"""

from __future__ import annotations

import collections
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor


# THE sampler lives in generation_utils so generate() and the engine share one
# implementation; re-exported here for the serving-facing API surface.
from ..models.generation_utils import fold_keys as _fold_keys, sample_rows


class Request:
    """One generation request tracked by the engine.

    Sampling params mirror ``generate()``: ``temperature=0`` (default) is
    greedy; otherwise temperature + optional top-p (nucleus) + top-k filter.
    ``seed`` (default: the request id) makes the request's sample stream
    reproducible regardless of batching or arrival order.
    """

    _counter = [0]

    def __init__(self, prompt_ids, max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None,
                 temperature: float = 0.0, top_p: float = 1.0,
                 top_k: int = 0, seed: Optional[int] = None):
        Request._counter[0] += 1
        self.rid = Request._counter[0]
        self.prompt = np.asarray(
            prompt_ids._data if isinstance(prompt_ids, Tensor) else prompt_ids
        ).reshape(-1).astype(np.int32)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.temperature = float(temperature)
        self.top_p = float(top_p)
        self.top_k = int(top_k)
        self.seed = int(seed if seed is not None else self.rid)
        self.output: List[int] = []
        self.done = False


class ContinuousBatchingEngine:
    def __init__(self, model, max_batch: int = 8, max_len: int = 512,
                 page_size: int = 64, block_size: int = 8,
                 prompt_buckets: Optional[Sequence[int]] = None):
        self.model = model
        self.max_batch = max_batch
        self.max_len = max_len
        self.page_size = page_size
        self.block_size = max(1, int(block_size))
        self.prompt_buckets = (sorted(int(b) for b in prompt_buckets)
                               if prompt_buckets else None)
        if self.prompt_buckets and self.prompt_buckets[-1] > max_len:
            raise ValueError(f"prompt bucket {self.prompt_buckets[-1]} "
                             f"exceeds max_len {max_len}")
        self.caches = model._init_paged_caches(max_batch, max_len, page_size)
        self._slots: List[Optional[Request]] = [None] * max_batch
        # per-slot NEXT write position (== tokens currently in the slot's cache)
        self._pos = np.zeros(max_batch, np.int32)
        self._last_tok = np.zeros(max_batch, np.int32)
        self._temps = np.zeros(max_batch, np.float32)
        self._tops = np.ones(max_batch, np.float32)
        self._topks = np.zeros(max_batch, np.int32)
        self._seeds = np.zeros(max_batch, np.int32)
        self._queue: collections.deque = collections.deque()
        self._finished: Dict[int, Request] = {}

        from ..jit.api import _collect_state

        _, tensors = _collect_state(model)
        self._params = [t._data for t in tensors]
        self._tensors = tensors
        self._jit_prefill: Dict[int, object] = {}
        self._jit_step = None

    # ---- public API ----
    def add_request(self, req: Request) -> int:
        if len(req.prompt) + req.max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt {len(req.prompt)} + max_new {req.max_new_tokens} "
                f"exceeds engine max_len {self.max_len}")
        if self.prompt_buckets and len(req.prompt) > self.prompt_buckets[-1]:
            raise ValueError(
                f"prompt {len(req.prompt)} exceeds largest prompt bucket "
                f"{self.prompt_buckets[-1]}")
        # family-specific length limits (e.g. GPT's learned position table) —
        # the same validation generate() applies
        validate = getattr(self.model, "_validate_generate", None)
        if validate is not None:
            validate(len(req.prompt), len(req.prompt) + req.max_new_tokens)
        self._queue.append(req)
        return req.rid

    def has_work(self) -> bool:
        return bool(self._queue) or any(s is not None for s in self._slots)

    def step(self):
        """Admit whatever fits, then advance active slots up to block_size
        tokens in ONE device program (one host sync per block)."""
        self._admit()
        live = [(i, r) for i, r in enumerate(self._slots) if r is not None]
        if not live:
            return
        active = np.array([s is not None for s in self._slots])
        # block length: never decode past a request's max_new_tokens or the
        # engine max_len (pages beyond the table would clamp-corrupt)
        n = self.block_size
        for i, r in live:
            n = min(n, r.max_new_tokens - len(r.output),
                    self.max_len - int(self._pos[i]))
        n = max(1, n)
        # parked rows decode at position 0 over slot-local pages — harmless
        pos_vec = jnp.asarray(np.where(active, self._pos, 1) - 1)
        toks = jnp.asarray(self._last_tok)
        if self._jit_step is None:
            from ..core import autograd_engine
            from ..jit.api import _Swap

            def run(params, toks, caches, pos_vec, seeds, temps, tops, topks,
                    n_steps):
                def body(carry, _):
                    tok, cs, pos = carry
                    with autograd_engine.no_grad(), _Swap(self._tensors,
                                                          params):
                        logits, cs = self.model.paged_token_step(tok, cs, pos)
                    keys = _fold_keys(seeds, pos + 1)
                    nxt = sample_rows(logits, keys, temps, tops, topks)
                    return (nxt, cs, pos + 1), nxt

                (tok, cs, _), out = jax.lax.scan(
                    body, (toks, caches, pos_vec), None, length=n_steps)
                return jnp.swapaxes(out, 0, 1), cs

            self._jit_step = jax.jit(run, static_argnames=("n_steps",))
        out, self.caches = self._jit_step(
            self._params, toks, self.caches, pos_vec,
            jnp.asarray(self._seeds), jnp.asarray(self._temps),
            jnp.asarray(self._tops), jnp.asarray(self._topks), n_steps=n)
        out = np.asarray(out)
        for i, req in enumerate(self._slots):
            if req is None:
                continue
            took = 0
            for j in range(n):
                tok = int(out[i, j])
                req.output.append(tok)
                took = j + 1
                if ((req.eos_token_id is not None and tok == req.eos_token_id)
                        or len(req.output) >= req.max_new_tokens):
                    req.done = True
                    break
            self._last_tok[i] = req.output[-1]
            self._pos[i] += took
            if req.done:
                self._finished[req.rid] = req
                self._slots[i] = None       # slot + its pages are free again
                self._pos[i] = 0
                self._temps[i] = 0.0

    def run_until_done(self, max_steps: int = 100000):
        steps = 0
        while self.has_work() and steps < max_steps:
            self.step()
            steps += 1
        return self.finished()

    def finished(self) -> Dict[int, Request]:
        out, self._finished = self._finished, {}
        return out

    # ---- internals ----
    def _admit(self):
        for i in range(self.max_batch):
            if self._slots[i] is not None or not self._queue:
                continue
            req = self._queue.popleft()
            self._temps[i] = req.temperature
            self._tops[i] = req.top_p
            self._topks[i] = req.top_k
            self._seeds[i] = req.seed
            first = self._prefill(i, req)
            self._slots[i] = req
            req.output.append(first)
            self._last_tok[i] = first
            self._pos[i] = len(req.prompt) + 1
            if ((req.eos_token_id is not None and first == req.eos_token_id)
                    or len(req.output) >= req.max_new_tokens):
                req.done = True
                self._finished[req.rid] = req
                self._slots[i] = None
                self._pos[i] = 0
                self._temps[i] = 0.0

    def _bucket(self, n: int) -> int:
        if not self.prompt_buckets:
            return n
        for b in self.prompt_buckets:
            if b >= n:
                return b
        return n  # unreachable: add_request validates against the last bucket

    def _prefill(self, slot: int, req: Request) -> int:
        """Prefill ONE slot's pages with the prompt; returns the first token.

        Compiles once per PADDED prompt length — with ``prompt_buckets`` that
        is once per bucket; the re-step of the last real token keeps bucketed
        numerics exact (see module docstring)."""
        n = len(req.prompt)
        padded = self._bucket(n)
        bucketed = padded != n
        ids = req.prompt
        if bucketed:
            ids = np.concatenate([ids, np.zeros(padded - n, np.int32)])
        # the re-step is compiled in only for genuinely padded prompts — an
        # exact-length prefill (incl. the prompt_buckets=None default) carries
        # no dead extra token step
        fn = self._jit_prefill.get((padded, bucketed))
        if fn is None:
            from ..core import autograd_engine
            from ..jit.api import _Swap

            def run(params, ids, kv, tables, true_len, seed, temp, top_p,
                    top_k, restep=bucketed):
                sub = {"kv": kv, "tables": tables}
                with autograd_engine.no_grad(), _Swap(self._tensors, params):
                    logits, sub = self.model._decode_chunk(
                        ids, sub, 0, None, None)
                    if restep:
                        # re-step the last REAL token at its true position:
                        # identical k/v rewrite, logits over the real prompt
                        # only (pad columns beyond true_len not yet attended)
                        last = jnp.take_along_axis(
                            ids, true_len[:, None] - 1, axis=1)[:, 0]
                        logits, sub = self.model.paged_token_step(
                            last, sub, true_len - 1)
                keys = _fold_keys(seed, true_len)
                nxt = sample_rows(logits, keys, temp, top_p,
                                  top_k)
                return nxt, sub["kv"]

            fn = self._jit_prefill[(padded, bucketed)] = jax.jit(
                run, static_argnames=("restep",))
        tables = self.caches["tables"][slot:slot + 1]
        kv = self.caches["kv"]
        first, new_kv = fn(
            self._params, jnp.asarray(ids)[None], kv, tables,
            jnp.asarray([n], jnp.int32), jnp.asarray([req.seed], jnp.int32),
            jnp.asarray([req.temperature], jnp.float32),
            jnp.asarray([req.top_p], jnp.float32),
            jnp.asarray([req.top_k], jnp.int32))
        self.caches = {"kv": new_kv, "tables": self.caches["tables"]}
        return int(first[0])
